"""Host-sync hot-path lint (ISSUE 8 tentpole, rule ``hot-sync``).

The pipelined scheduler's overlap win (PR 2: segment N+1 dispatches from
device-resident state while the host harvests segment N) survives only
as long as nothing on the dispatch path forces an early device sync. A
single stray ``.item()`` or ``np.asarray(device_array)`` quietly
re-serializes the whole pipeline — throughput regresses with no error
anywhere. This rule is the static guarantee behind the measured overlap
ratio:

A class (or module) DECLARES its dispatch-path roots::

    _HOT_ROOTS = ("step", "_dispatch_segment")

The analyzer computes the functions reachable from those roots — via
``self.method()`` calls, direct module-function calls, and module-level
aliases (``_decode_segment_jit -> _decode_segment``) — and flags, in
every reachable function, the host-sync shapes:

  * ``.item()`` — scalar readback, a full device sync;
  * ``jax.device_get(...)`` / ``.block_until_ready()`` — explicit syncs;
  * ``np.asarray`` / ``np.array`` / ``np.ascontiguousarray`` on
    anything that is not a provable host container (list/tuple literal
    or comprehension) — a device array argument devolves to device_get;
  * ``float(x)`` where ``x`` is a call/subscript/attribute expression —
    the implicit scalar readback shape.

Harvest points are ANNOTATED, not inferred: a ``def`` carrying
``# egpt-check: harvest -- reason`` (on the def line or the line above)
is where the design says the host blocks (``_harvest_segment`` fetching
a settled segment; the admission NaN-quarantine readbacks). Annotated
functions are exempt and the reachability walk stops there — everything
downstream runs on already-harvested host state.

Static limits: the walk is per-file (cross-module calls are attribute
calls it does not follow) and jitted bodies reached by alias ARE walked
— a host sync inside a traced function would be a trace-time sync,
which is just as wrong.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from eventgpt_tpu.analysis.core import (Context, Finding, Rule,
                                        class_literal, is_harvest)

HOT_ROOTS_ATTR = "_HOT_ROOTS"

_NP_NAMES = ("np", "numpy")
_NP_SYNC_FNS = ("asarray", "array", "ascontiguousarray")
_HOST_ARG_NODES = (ast.List, ast.ListComp, ast.Tuple, ast.Constant,
                   ast.Dict, ast.GeneratorExp)


def _callee_name(call: ast.Call) -> Optional[str]:
    """'self.m' for method calls, 'f' for direct calls, else None."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
            and fn.value.id == "self"):
        return f"self.{fn.attr}"
    return None


def _module_aliases(tree: ast.AST,
                    functions: Dict[str, ast.AST]) -> Dict[str, str]:
    """Module-level ``A = <expr referencing function F>`` -> {A: F}:
    how ``_decode_segment_jit = functools.partial(jax.jit, ...)
    (_decode_segment)`` resolves back to the wrapped body."""
    out: Dict[str, str] = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if not (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        refs = [n.id for n in ast.walk(node.value)
                if isinstance(n, ast.Name) and n.id in functions]
        if len(refs) == 1:
            out[node.targets[0].id] = refs[0]
    return out


class HotSyncRule(Rule):
    id = "hot-sync"
    doc = ("functions reachable from the declared dispatch-path roots "
           "(_HOT_ROOTS) contain no host syncs except at annotated "
           "harvest points")

    def run(self, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        for s in ctx.sources:
            if s.tree is None:
                continue
            self._check_module(s, findings)
        return findings

    # -- per-module walk --------------------------------------------------

    def _check_module(self, s, findings: List[Finding]) -> None:
        module_fns: Dict[str, ast.AST] = {
            n.name: n for n in s.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        aliases = _module_aliases(s.tree, module_fns)
        classes = [n for n in ast.walk(s.tree)
                   if isinstance(n, ast.ClassDef)]
        # Module-level roots, then per-class roots.
        declared = False
        for cls in classes:
            try:
                roots, line = class_literal(cls, HOT_ROOTS_ATTR)
            except ValueError as e:
                findings.append(Finding(
                    self.id, s.rel, cls.lineno, f"{cls.name}: {e}"))
                continue
            if roots is None:
                continue
            declared = True
            if not isinstance(roots, (tuple, list)) or not all(
                    isinstance(r, str) for r in roots):
                findings.append(Finding(
                    self.id, s.rel, line,
                    f"{cls.name}: {HOT_ROOTS_ATTR} must be a tuple of "
                    f"method/function names"))
                continue
            methods = {n.name: n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            missing = [r for r in roots
                       if r not in methods and r not in module_fns
                       and r not in aliases]
            for r in missing:
                findings.append(Finding(
                    self.id, s.rel, line,
                    f"{cls.name}: {HOT_ROOTS_ATTR} names unknown "
                    f"function {r!r}"))
            self._walk_hot_set(
                s, [r for r in roots if r not in missing],
                methods, module_fns, aliases, findings)
        del declared

    def _walk_hot_set(self, s, roots, methods, module_fns, aliases,
                      findings: List[Finding]) -> None:
        # key space: "self.<name>" for methods, "<name>" for module fns.
        def resolve(name: str):
            if name.startswith("self."):
                return methods.get(name[5:]), name
            if name in module_fns:
                return module_fns[name], name
            if name in aliases:
                return module_fns.get(aliases[name]), aliases[name]
            return None, name

        seen: Set[str] = set()
        queue: List[str] = []
        for r in roots:
            queue.append(f"self.{r}" if r in methods else r)
        while queue:
            name = queue.pop()
            fn, key = resolve(name)
            if fn is None or key in seen:
                continue
            seen.add(key)
            harvest, _reason = is_harvest(s, fn)
            if harvest:
                continue  # annotated sync point: exempt, walk stops
            self._check_hot_fn(s, fn, key, findings)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = _callee_name(node)
                    if callee is not None:
                        queue.append(callee)

    # -- banned shapes ----------------------------------------------------

    def _check_hot_fn(self, s, fn, key: str,
                      findings: List[Finding]) -> None:
        where = key.replace("self.", "")
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            msg = None
            if isinstance(f, ast.Attribute):
                if f.attr == "item" and not node.args:
                    msg = ".item() is a full device sync"
                elif f.attr == "device_get":
                    msg = "jax.device_get forces a host readback"
                elif f.attr == "block_until_ready":
                    msg = "block_until_ready stalls the dispatch path"
                elif (f.attr in _NP_SYNC_FNS
                      and isinstance(f.value, ast.Name)
                      and f.value.id in _NP_NAMES
                      and not (node.args and isinstance(
                          node.args[0], _HOST_ARG_NODES))):
                    msg = (f"np.{f.attr} on a possibly device-resident "
                           f"value devolves to device_get")
            elif (isinstance(f, ast.Name) and f.id == "float"
                  and len(node.args) == 1
                  and isinstance(node.args[0],
                                 (ast.Call, ast.Subscript))):
                msg = ("float(<array expr>) is an implicit scalar "
                       "readback")
            if msg is not None:
                findings.append(Finding(
                    self.id, s.rel, node.lineno,
                    f"host sync in dispatch-path function "
                    f"'{where}': {msg}",
                    hint="move it behind an annotated harvest point "
                         "('# egpt-check: harvest -- reason' on the "
                         "def) or waive with justification"))
