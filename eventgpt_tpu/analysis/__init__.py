"""egpt-check: the repo's unified static-analysis suite (ISSUE 8).

``scripts/egpt_check.py`` is the runner; ``ALL_RULES`` is the
registry — three analyzers born in this PR (lock-discipline race
detector, host-sync hot-path lint, jit-hygiene lint) plus the five
telemetry rules migrated from ``scripts/lint_telemetry.py``. The shared
walk, the ``Finding`` shape, and the waiver grammar live in ``core``.

Deliberately jax-free and stdlib-only: the suite must run (and the fast
tier must gate on it) anywhere the repo checks out, before any device
exists.
"""

from eventgpt_tpu.analysis.core import (Context, Finding, Rule,
                                        load_sources, render_json,
                                        render_text, run_checks,
                                        unwaived)
from eventgpt_tpu.analysis.hot_path import HotSyncRule
from eventgpt_tpu.analysis.jit_hygiene import JitHygieneRule
from eventgpt_tpu.analysis.lock_discipline import LockDisciplineRule
from eventgpt_tpu.analysis.telemetry_rules import TELEMETRY_RULES

ALL_RULES = (LockDisciplineRule(), HotSyncRule(),
             JitHygieneRule()) + TELEMETRY_RULES

__all__ = [
    "ALL_RULES", "Context", "Finding", "Rule", "load_sources",
    "render_json", "render_text", "run_checks", "unwaived",
    "HotSyncRule", "JitHygieneRule", "LockDisciplineRule",
    "TELEMETRY_RULES",
]
