"""Jit-hygiene lint (ISSUE 8 tentpole, rule ``jit-cache``).

Executable management is a convention in this repo, learned the hard
way (PERFORMANCE.md, DISTRIBUTED.md):

  * configuration is DECLARED at the jit site — ``static_argnames`` /
    ``static_argnums`` / ``donate_argnums`` / ``donate_argnames`` /
    ``out_shardings`` / ``in_shardings`` — because an undeclared donate
    silently doubles resident HBM and an unpinned out-sharding breaks
    donated-cache aliasing (a second full-size cache per segment, the
    ``_get_sharded_prefill`` reasoning); explicit empty pins
    (``static_argnames=()``) count — they say the author considered
    them;
  * executables for shape-bucketed callables land in a CACHE keyed by
    the bucket — the ``@functools.lru_cache`` ``_get_sharded_*`` getter
    pattern — never rebuilt per call: ``jax.jit(f)`` constructed inside
    a plain function re-traces and re-compiles on EVERY invocation.

This rule scans every ``jax.jit`` / ``pjit`` site in ``eventgpt_tpu/``
(direct calls, ``functools.partial(jax.jit, ...)`` applications, and
bare ``@jax.jit`` decorators) and flags:

  * **bare jit** — a site declaring none of the config kwargs, unless
    it lives inside an lru_cache'd getter (there the closure IS the
    config, resolved once per cache key);
  * **untracked executable creation** — a non-decorator ``jax.jit(...)``
    call inside a plain (un-cached) function: re-trace + re-compile per
    call, the exact failure mode the ``_get_sharded_*`` pattern exists
    to make impossible;
  * **jit in a loop** — the same inside ``for``/``while``: a recompile
    per iteration, the worst case.

The factory form — ``@functools.partial(jax.jit, ...)`` decorating a
nested ``def`` inside a ``make_*`` builder (train steps) — is allowed
when configured: the executable's lifetime is the returned closure's,
built once per trainer.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from eventgpt_tpu.analysis.core import Context, Finding, Rule

_CONFIG_KWARGS = ("static_argnums", "static_argnames", "donate_argnums",
                  "donate_argnames", "out_shardings", "in_shardings",
                  "device", "backend")
_CACHE_DECOS = ("lru_cache", "cache")


def _is_jit_ref(node: ast.AST) -> bool:
    """``jax.jit`` / ``pjit`` referenced (not called) — attribute or
    bare name."""
    if isinstance(node, ast.Attribute):
        return node.attr in ("jit", "pjit")
    if isinstance(node, ast.Name):
        return node.id == "pjit"
    return False


def _partial_of_jit(call: ast.Call) -> bool:
    """``functools.partial(jax.jit, **cfg)`` — the decorator idiom."""
    fn = call.func
    is_partial = (isinstance(fn, ast.Attribute) and fn.attr == "partial") \
        or (isinstance(fn, ast.Name) and fn.id == "partial")
    return bool(is_partial and call.args and _is_jit_ref(call.args[0]))


def _config_kwargs(call: ast.Call) -> List[str]:
    return [kw.arg for kw in call.keywords if kw.arg in _CONFIG_KWARGS]


def _has_cache_deco(fn: ast.AST) -> bool:
    for deco in getattr(fn, "decorator_list", ()):
        target = deco.func if isinstance(deco, ast.Call) else deco
        name = (target.attr if isinstance(target, ast.Attribute)
                else target.id if isinstance(target, ast.Name) else "")
        if name in _CACHE_DECOS:
            return True
    return False


class JitHygieneRule(Rule):
    id = "jit-cache"
    doc = ("every jax.jit/pjit site declares its static/donate/sharding "
           "config and lands its executable at module scope or in an "
           "lru_cache'd getter (_get_sharded_* pattern); no per-call or "
           "in-loop executable creation")

    def run(self, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        for s in ctx.sources:
            if s.tree is None or not s.rel.startswith("eventgpt_tpu/"):
                continue
            parents = s.parents()
            for node in ast.walk(s.tree):
                if isinstance(node, ast.Call) and _is_jit_ref(node.func):
                    # jax.jit(f, **cfg) — direct executable creation.
                    self._check(s, node, _config_kwargs(node), parents,
                                findings, call_form=True)
                elif isinstance(node, ast.Call) and _partial_of_jit(node):
                    # functools.partial(jax.jit, **cfg) — decorator /
                    # module-application idiom; the partial itself is
                    # config declaration, its application creates the
                    # executable wherever it happens.
                    self._check(s, node, _config_kwargs(node), parents,
                                findings, call_form=False)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    for deco in node.decorator_list:
                        if _is_jit_ref(deco):
                            # bare @jax.jit decorator: no Call node
                            # exists, so it needs its own branch.
                            self._check(s, deco, [], parents, findings,
                                        call_form=False,
                                        decorated=node)
        return findings

    def _context(self, node: ast.AST, parents,
                 decorated=None) -> Tuple[list, bool, bool]:
        """(enclosing function chain, in_loop, is_decorator)."""
        chain: list = []
        in_loop = False
        is_deco = decorated is not None
        cur = decorated if decorated is not None else node
        while True:
            p = parents.get(cur)
            if p is None:
                break
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if cur in p.decorator_list:
                    is_deco = True
                else:
                    chain.append(p)
            if isinstance(p, (ast.For, ast.While)):
                in_loop = True
            cur = p
        return chain, in_loop, is_deco

    def _check(self, s, node: ast.AST, cfg: List[str], parents,
               findings: List[Finding], call_form: bool,
               decorated=None) -> None:
        chain, in_loop, is_deco = self._context(node, parents, decorated)
        cached = any(_has_cache_deco(fn) for fn in chain)
        if not cfg and not cached:
            where = ("module scope" if not chain
                     else f"'{chain[0].name}'")
            findings.append(Finding(
                self.id, s.rel, node.lineno,
                f"bare jax.jit at {where}: none of "
                f"static_argnums/static_argnames/donate/out_shardings "
                f"declared",
                hint="declare the pins (explicit empty tuples count) "
                     "or move the site into an lru_cache'd getter"))
        if not chain:
            return  # module scope: one executable for the process life
        if cached or is_deco:
            return  # bucket-keyed getter / factory closure: tracked
        if in_loop:
            findings.append(Finding(
                self.id, s.rel, node.lineno,
                "jax.jit executable created inside a loop — retrace + "
                "recompile per iteration",
                hint="hoist into an lru_cache'd _get_* getter keyed by "
                     "the shape bucket"))
        elif call_form:
            findings.append(Finding(
                self.id, s.rel, node.lineno,
                "untracked executable creation: jax.jit(...) inside a "
                "plain function re-traces and re-compiles per call",
                hint="land it in an lru_cache'd getter (the "
                     "_get_sharded_* pattern) or at module scope"))
