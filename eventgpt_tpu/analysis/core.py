"""egpt-check core: the shared machinery every analyzer rides (ISSUE 8).

``scripts/lint_telemetry.py``'s five rules proved AST lints catch real
drift cheaply; this package is that seed grown into the repo's
correctness-tooling layer. One walk parses the runtime tree ONCE into
``Source`` records (path, text, AST, parent links, waivers); each rule
is a ``Rule`` subclass whose ``run(ctx)`` returns ``Finding`` objects
(file:line + message + fix hint). The runner (``run_checks`` /
``scripts/egpt_check.py``) applies waivers, renders text or JSON, and
exits non-zero on unwaived findings — the tier-1 contract is that the
shipped tree is CLEAN (``tests/test_egpt_check.py::test_repo_self_check``).

Waivers are in-source and must carry a justification — the grammar is
``egpt-check: ignore[<rule>] -- <reason>`` in a trailing comment. The
comment lives on the offending line or the line directly above; the
rule id in brackets must name a registered rule (several comma-separate).
A waiver with no ``-- reason`` is itself a finding (rule ``waiver``): an
unexplained suppression is exactly the silent rot this tool exists to
stop.

Annotations the rules read (details in each rule module and in
OBSERVABILITY.md "Static analysis"):

  * ``_GUARDED_BY = {"_attr": "_lock", "_stats": "_lock/w"}`` — class
    attribute mapping guarded attributes to their lock; ``/w`` guards
    writes only (the lock-free-snapshot read pattern).
  * ``_EXTERNAL_LOCK = "Owner._lock"`` — the whole class is serialized
    by its owner's lock (``ContinuousBatcher`` under ``ServingEngine``).
  * ``_HOT_ROOTS = ("step", "_dispatch_segment")`` — dispatch-path roots
    for the host-sync lint's reachability walk.
  * ``# egpt-check: harvest -- reason`` on/above a ``def`` — an
    annotated harvest point where host readbacks are the design.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

# Trees the suite scans (tests/ stays out on purpose: fixtures and
# private test registries would drown every rule in noise; the telemetry
# fault-coverage rule reads tests/ itself, for arming evidence only).
SCAN_TREES = ("eventgpt_tpu", "scripts", "bench.py")

_WAIVER_RE = re.compile(
    r"#\s*egpt-check:\s*ignore\[([A-Za-z0-9_,\- ]+)\]\s*(?:--\s*(.*))?")
_HARVEST_RE = re.compile(r"#\s*egpt-check:\s*harvest(?:\s*--\s*(.*))?")


@dataclass
class Finding:
    """One violation: ``file:line`` + rule id + message + fix hint."""
    rule: str
    file: str            # repo-relative, '/'-separated
    line: int            # 1-based; 0 = file-level
    message: str
    hint: str = ""
    waived: bool = False
    waiver_reason: str = ""

    def render(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        s = f"{loc}: [{self.rule}] {self.message}"
        if self.hint:
            s += f" (fix: {self.hint})"
        if self.waived:
            s += f" [waived: {self.waiver_reason}]"
        return s

    def as_dict(self) -> Dict:
        return {
            "rule": self.rule, "file": self.file, "line": self.line,
            "message": self.message, "hint": self.hint,
            "waived": self.waived,
            **({"waiver_reason": self.waiver_reason} if self.waived else {}),
        }


@dataclass
class Source:
    """One parsed file of the scanned tree. ``tree`` is None when the
    file does not parse (the runner emits an unparseable finding).
    ``waivers``/``harvests`` are line -> payload maps; a marker on line
    N covers findings on N and N+1 (comment-above style)."""
    rel: str
    path: str
    text: str
    tree: Optional[ast.AST]
    parse_error: str = ""
    waivers: Dict[int, Tuple[Tuple[str, ...], str]] = field(
        default_factory=dict)
    harvests: Dict[int, str] = field(default_factory=dict)
    _parents: Optional[Dict[ast.AST, ast.AST]] = None

    def parents(self) -> Dict[ast.AST, ast.AST]:
        """Child -> parent node map, built lazily once per file."""
        if self._parents is None:
            self._parents = {}
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    for child in ast.iter_child_nodes(node):
                        self._parents[child] = node
        return self._parents

    def line(self, n: int) -> str:
        lines = self.text.splitlines()
        return lines[n - 1] if 1 <= n <= len(lines) else ""


@dataclass
class Context:
    """What every rule gets: the parsed tree plus the repo root (rules
    that need out-of-tree evidence — OBSERVABILITY.md, tests/ — read it
    themselves)."""
    root: str
    sources: List[Source]

    def source(self, rel: str) -> Optional[Source]:
        for s in self.sources:
            if s.rel == rel or s.rel.endswith(rel):
                return s
        return None


#: Every rule id any imported Rule subclass registered — waiver
#: validation checks against THIS set, not the running subset, so a
#: telemetry-only run does not flag a lock waiver as unknown.
KNOWN_RULE_IDS = {"waiver", "parse"}


class Rule:
    """One analyzer. ``id`` names it in waiver comments and reports;
    ``run`` returns findings (waiver application is the runner's)."""

    id: str = ""
    doc: str = ""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if getattr(cls, "id", ""):
            KNOWN_RULE_IDS.add(cls.id)

    def run(self, ctx: Context) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


def _scan_files(root: str) -> List[str]:
    out: List[str] = []
    for scan in SCAN_TREES:
        p = os.path.join(root, scan)
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, files in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            out.extend(os.path.join(dirpath, f) for f in sorted(files)
                       if f.endswith(".py"))
    return sorted(out)


def _scan_markers(src: Source) -> None:
    """Populate the waiver / harvest line maps from the raw text (the
    AST drops comments, so markers are a line-scan)."""
    for i, line in enumerate(src.text.splitlines(), start=1):
        m = _WAIVER_RE.search(line)
        if m is not None:
            rules = tuple(r.strip() for r in m.group(1).split(",")
                          if r.strip())
            reason = (m.group(2) or "").strip()
            src.waivers[i] = (rules, reason)
        h = _HARVEST_RE.search(line)
        if h is not None:
            src.harvests[i] = (h.group(1) or "").strip()


def load_sources(root: str) -> List[Source]:
    """The shared walk: parse every scanned file once; every rule then
    reads the same ``Source`` records."""
    sources: List[Source] = []
    for path in _scan_files(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path) as f:
            text = f.read()
        try:
            tree = ast.parse(text, rel)
            err = ""
        except SyntaxError as e:
            tree, err = None, str(e)
        src = Source(rel=rel, path=path, text=text, tree=tree,
                     parse_error=err)
        _scan_markers(src)
        sources.append(src)
    return sources


def class_literal(cls: ast.ClassDef, name: str):
    """Pure-literal class attribute ``name`` (``_GUARDED_BY`` /
    ``_HOT_ROOTS`` grammar: ast.literal_eval, no imports). Handles both
    ``X = {...}`` and the dataclass-safe ``X: ClassVar[...] = {...}``.
    Returns (value, lineno) or (None, 0); raises ValueError on a
    non-literal value (the annotation contract is violated)."""
    for node in cls.body:
        tgt = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            tgt = node.target.id
        if tgt != name:
            continue
        try:
            return ast.literal_eval(node.value), node.lineno
        except (ValueError, SyntaxError):
            raise ValueError(
                f"{name} must be a pure literal (ast.literal_eval)")
    return None, 0


def is_harvest(src: Source, fn: ast.AST) -> Tuple[bool, str]:
    """A function is an annotated harvest point when its ``def`` line,
    the line above it, or the line above its first decorator carries the
    ``# egpt-check: harvest -- reason`` marker."""
    lines = {fn.lineno, fn.lineno - 1}
    deco = getattr(fn, "decorator_list", None)
    if deco:
        lines.add(deco[0].lineno - 1)
    for ln in lines:
        if ln in src.harvests:
            return True, src.harvests[ln]
    return False, ""


def _apply_waivers(sources: Sequence[Source],
                   findings: List[Finding]) -> List[Finding]:
    by_rel = {s.rel: s for s in sources}
    out: List[Finding] = []
    for f in findings:
        src = by_rel.get(f.file)
        if src is not None and f.line:
            for ln in (f.line, f.line - 1):
                w = src.waivers.get(ln)
                if w is not None and f.rule in w[0]:
                    f.waived = True
                    f.waiver_reason = w[1]
                    break
        out.append(f)
    return out


def _waiver_findings(sources: Sequence[Source]) -> List[Finding]:
    """Malformed waivers are findings too: a suppression with no reason
    (or naming no registered rule) must not silently disable a check."""
    out: List[Finding] = []
    for src in sources:
        for ln, (rules, reason) in sorted(src.waivers.items()):
            if not reason:
                out.append(Finding(
                    "waiver", src.rel, ln,
                    "waiver without a justification",
                    hint="write '# egpt-check: ignore[<rule>] -- why it "
                         "is safe'"))
            unknown = [r for r in rules if r not in KNOWN_RULE_IDS]
            if unknown:
                out.append(Finding(
                    "waiver", src.rel, ln,
                    f"waiver names unknown rule(s) {unknown} "
                    f"(registered: {sorted(KNOWN_RULE_IDS)})",
                    hint="use a registered rule id"))
    return out


def run_checks(root: str, rules: Sequence[Rule],
               sources: Optional[List[Source]] = None) -> List[Finding]:
    """Run every rule over one shared parse of ``root``. Returns ALL
    findings, waived ones flagged — callers gate on the unwaived subset
    (``unwaived()``)."""
    if sources is None:
        sources = load_sources(root)
    ctx = Context(root=root, sources=sources)
    findings: List[Finding] = []
    for src in sources:
        if src.tree is None:
            findings.append(Finding(
                "parse", src.rel, 0, f"unparseable ({src.parse_error})"))
    for rule in rules:
        findings.extend(rule.run(ctx))
    findings.extend(_waiver_findings(sources))
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
    return _apply_waivers(sources, findings)


def unwaived(findings: Sequence[Finding]) -> List[Finding]:
    return [f for f in findings if not f.waived]


def render_text(findings: Sequence[Finding],
                show_waived: bool = False) -> str:
    live = unwaived(findings)
    waived = [f for f in findings if f.waived]
    lines = [f.render() for f in live]
    if show_waived:
        lines += [f.render() for f in waived]
    lines.append(f"egpt-check: {len(live)} finding(s), "
                 f"{len(waived)} waived")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding],
                rules: Sequence[Rule]) -> str:
    """The ``--json`` mode bench/CI tooling diffs across PRs: stable
    keys, per-rule counts, waived findings carried separately."""
    live = unwaived(findings)
    waived = [f for f in findings if f.waived]
    counts: Dict[str, int] = {r.id: 0 for r in rules}
    for f in live:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return json.dumps({
        "findings": [f.as_dict() for f in live],
        "waived": [f.as_dict() for f in waived],
        "counts": counts,
        "total": len(live),
        "total_waived": len(waived),
    }, indent=2)
