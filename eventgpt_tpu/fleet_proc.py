"""Process-fleet serving: worker processes behind an RPC coordinator
(ISSUE 11).

PR 7's ``Fleet`` runs N replicas as THREADS in one process — one
weight tree, one jax runtime, one failure domain: a process death (the
exact event SIGKILL chaos injects here) kills every replica at once.
This module crosses the process boundary, step 1 of ROADMAP item 1: a
``ProcFleet`` coordinator with the same client surface as ``Fleet``
(so ``cli.serve.make_handler`` serves it unchanged) that spawns N
worker PROCESSES, each owning a full ``ServingEngine`` + model + jax
runtime, and talks to them over the minimal length-prefixed
JSON-over-TCP RPC in ``rpc.py``. No jax collectives cross the
boundary — each worker has its own device state — so the whole tier
runs in tier-1 on CPU, and ``export_requests``-over-RPC is the exact
seam the later prefill/decode KV handoff (DistServe / Splitwise) will
reuse: today the drain moves a request's RECORD, the disaggregated
tier will move its record plus KV.

Robustness is the headline, in four layers:

1. **Every RPC edge is bounded.** Per-op deadlines, bounded
   exponential backoff + jitter, mutating ops never blind-retried
   (``rpc.call``). Fault sites ``procfleet.rpc`` (a trip is a
   transport failure the retry loop must absorb), ``procfleet.spawn``
   (a trip fails that spawn attempt — the backoff/respawn path
   handles it) and ``procfleet.worker_kill`` (the trip IS the scripted
   SIGKILL of the busiest worker) make every layer chaos-testable.
2. **Liveness is observed three ways**: heartbeat files (each worker
   writes the trainer-format beat under ``--heartbeat_dir/replicaN``,
   the PR 7 convention), RPC probe timeouts (lock-free ops only — a
   worker busy compiling is SLOW, not DEAD), and ``Popen.poll()`` exit
   codes. A stale/unreachable worker is DRAINED while it still
   answers: ``export_requests`` over RPC strips its queued + in-flight
   requests and re-routes them mid-decode (committed tokens discarded;
   greedy chains are deterministic per request, so the survivor's
   chain is byte-identical to an uninterrupted run — the PR 7 bar). A
   hard-dead worker (SIGKILL) gets the REDO path: the coordinator
   re-submits from its own records, and the journey recorder charges
   the abandoned assignment's wall time to ``failover_redo_s``
   (``worker_lost`` / ``respawn`` joined ``EVENT_KINDS`` for this).
3. **Respawn with a crash-loop breaker.** A dead slot respawns after a
   per-slot exponential backoff; K crashes inside ``crash_window_s``
   trip the slot's breaker — the fleet gives the slot up and degrades
   capacity instead of burning CPU on a doomed spawn loop. ``/health``
   stays green while ≥ 1 worker is routable.
4. **Shutdown drains.** The coordinator waits (bounded) for in-flight
   requests, then asks every worker to shut down over RPC before
   escalating to terminate/kill.

Prefix-affinity routing reuses ``fleet.affinity_key`` verbatim (the
``PrefixCache``'s own identity), so a session keeps hitting the worker
whose radix cache holds its head. Per-worker component bytes surface
through ``/fleet`` and ``GET /memory`` — each worker reports its OWN
process ledger (unlike the thread fleet there is no shared tree: N
processes = N weight copies, the honest cost of the failure-domain
boundary).

Cross-process clocks: ``perf_counter`` is per-process, so the
coordinator stitches journeys from DURATIONS, not absolute stamps —
the final assignment's worker-measured phase decomposition plus
``failover_redo_s`` = (coordinator time of the final assignment −
coordinator submit time). The phase-sum invariant (phases sum to the
reported e2e exactly) holds by construction; RPC transport time on the
final assignment lands in the small gap between the journey's e2e and
the client-observed wall time (documented, not hidden).

Streaming: the coordinator's streams are DELIVER-AT-FINISH (one
cumulative delta + the terminal sentinel). Nothing leaves the process
before the request is terminal, which is exactly why — unlike the
in-process fleet — streamed requests CAN fail over here.

Prefill/decode disaggregation (ISSUE 17): ``--proc_fleet_roles P:D``
splits the fleet into PREFILL workers (chunked/batched admission only
— their scheduler never dispatches a decode segment) and DECODE
workers. New requests route to the prefill pool (prefix affinity
unchanged — the radix caches live where the prompts land); when a
prefill worker finishes admission it gathers the request's paged block
run (the PR 16 spill record: block-table-named KV at SEQ_BUCKET grain
+ int8 scale planes + sampling state + the closed prefill-leg journey)
into a handoff outbox. The coordinator's supervisor pumps that outbox:
``collect_handoffs`` pulls records over the raw-binary RPC frame (KV
bytes ride verbatim, no b64 inflation), ``import_handoff`` ships each
to the decode worker with the most free block-pool bytes, and
``ack_handoffs`` releases the prefill side's replay copy only after
the ship lands. Every ship attempt probes the ``procfleet.handoff``
fault site; a failed attempt retries against other decode workers
(bounded by ``handoff_retries``) and then falls back to the REDO path
— never a double splice: the decode handler dedups imports on the
coordinator-assigned ``hid`` token, so a retried ship whose first ack
was lost re-serves the same worker rid. Greedy chains are
byte-identical to a colocated run (the splice rides the same paged
admission executable). Journeys stitch THREE legs from durations:
prefill phases + ``handoff_s`` (coordinator collect->import wall time)
+ decode phases + ``failover_redo_s``, exact-sum as ever.

A jax-free STUB worker (``python -m eventgpt_tpu.fleet_proc
--stub_worker``) serves the same RPC surface over a deterministic fake
engine, so the coordinator's spawn/retry/respawn/crash-loop logic is
testable in milliseconds; the chain-identity and SIGKILL chaos tests
run real ``cli.serve --worker`` processes.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from eventgpt_tpu import faults, rpc
from eventgpt_tpu.fleet import affinity_key
from eventgpt_tpu.obs import journey as obs_journey
from eventgpt_tpu.obs import metrics as obs_metrics
from eventgpt_tpu.obs import series as obs_series
from eventgpt_tpu.obs import trace as obs_trace

def _map_remote(e: rpc.RpcRemoteError) -> Exception:
    """Remote exception type name -> the local exception the serving
    stack's callers already handle."""
    if e.type_name == "QueueFullError":
        # Re-raise as the REAL engine exception so make_handler's
        # except clause catches it (lazy import: jax-heavy module).
        from eventgpt_tpu.serve import QueueFullError

        return QueueFullError(e.remote_msg)
    if e.type_name == "ValueError":
        return ValueError(e.remote_msg)
    return RuntimeError(f"worker error: {e}")


# -- worker side -----------------------------------------------------------

class WorkerHandler:
    """The RPC op table over one ``ServingEngine`` (or the test stub).

    Ops: submit_ids / try_result / try_results / try_status / cancel /
    export_requests / snapshot / stats / memory / journey / set_prefix /
    reset_stats / ping / shutdown / collect_handoffs / ack_handoffs /
    import_handoff.

    ``try_result`` is made IDEMPOTENT here: the engine pops a delivered
    answer, so a retried poll whose first response was lost would find
    nothing and the request would hang forever. Delivered results are
    kept in a bounded replay cache so the retry re-serves the same
    record (the coordinator-side dedup key is the rid).

    The handoff ops get the same treatment from both sides (ISSUE 17):
    ``collect_handoffs`` parks popped records in ``_handoff_unacked``
    and re-serves them until ``ack_handoffs`` — a collect response lost
    to a transport fault replays instead of stranding KV; and
    ``import_handoff`` dedups on the coordinator-assigned ``hid`` in a
    bounded ``_imported`` cache, so a retried ship whose first response
    was lost returns the original rid instead of splicing twice.
    """

    # Lock discipline (egpt-check rule ``lock``): the replay caches are
    # written from concurrent RPC connection threads.
    _GUARDED_BY = {"_delivered": "_lock", "_handoff_unacked": "_lock",
                   "_imported": "_lock"}

    REPLAY_CAP = 4096

    def __init__(self, engine):
        self.engine = engine
        self.stop_event = threading.Event()
        self._lock = threading.Lock()
        self._delivered: Dict[int, dict] = {}
        self._handoff_unacked: Dict[int, dict] = {}
        self._imported: Dict[str, int] = {}

    def _result_record(self, rid: int) -> Optional[dict]:
        with self._lock:
            if rid in self._delivered:
                return self._delivered[rid]
        got = self.engine.try_result(rid)
        if got is None:
            return None
        tokens, status = got
        rec = {
            "tokens": tokens, "status": status,
            "stats": dict(self.engine.batcher.request_stats.get(rid, {})),
            # The worker-side flight-recorder timeline (phases included
            # once finished): the coordinator stitches failover_redo_s
            # on top of these worker-measured durations.
            "journey": self.engine.journey(rid),
        }
        with self._lock:
            self._delivered[rid] = rec
            while len(self._delivered) > self.REPLAY_CAP:
                self._delivered.pop(next(iter(self._delivered)))
        return rec

    def __call__(self, op: str, p: dict) -> Any:
        eng = self.engine
        if op == "ping":
            return {"pid": os.getpid(), "alive": eng.alive}
        if op == "submit_ids":
            return eng.submit_ids(
                list(p["input_ids"]), p["pixel_values"],
                int(p["max_new_tokens"]),
                deadline_s=p.get("deadline_s"), slo=p.get("slo"))
        if op == "try_result":
            return self._result_record(int(p["rid"]))
        if op == "try_results":
            return {str(rid): self._result_record(int(rid))
                    for rid in p["rids"]}
        if op == "try_status":
            return eng.try_status(int(p["rid"]))
        if op == "cancel":
            return eng.cancel(int(p["rid"]))
        if op == "export_requests":
            # kill(): deliver finished work to the replay path, park the
            # scheduler, strip + return every unfinished request — the
            # graceful-drain half of the failover story. The process
            # stays up so the coordinator can still collect
            # finished-but-uncollected answers before shutdown.
            return eng.kill()
        if op == "snapshot":
            s = dict(eng.snapshot())
            s["breaker_open"] = eng.breaker_open()
            s["alive"] = eng.alive
            s["goodput_ratio"] = eng.goodput_ratio()
            s["n_faults"] = eng.n_faults
            s["n_restarts"] = eng.n_restarts
            pc = dict(eng.batcher.prefix_cache_stats())
            pc.pop("entries", None)  # per-entry dumps don't aggregate
            s["prefix_cache"] = pc
            # Active alert rules ride the probe snapshot (ISSUE 15), so
            # the coordinator's /stats can show fleet-wide health state
            # without an extra RPC fan-out per poll.
            s["alerts_active"] = eng.alerts().get("active", [])
            return s
        if op == "stats":
            return eng.stats()
        if op == "memory":
            return eng.memory_stats()
        if op == "series":
            # Time-series pull (ISSUE 15): the worker's own store, ages
            # already duration-aligned to the worker's clock — absolute
            # perf_counter values never cross the process boundary.
            return eng.series(window_s=p.get("window_s"), n=p.get("n"))
        if op == "alerts":
            return eng.alerts()
        if op == "journey":
            return eng.journey(int(p["rid"]))
        if op == "set_prefix":
            return eng.set_prefix(p["prefix_prompt"],
                                  p.get("pixel_values"))
        if op == "reset_stats":
            b = eng.batcher
            if hasattr(b, "reset_serving_stats"):
                b.reset_serving_stats()
            obs_metrics.REGISTRY.reset()
            try:
                from eventgpt_tpu.obs import memory as obs_memory

                obs_memory.LEDGER.reset_peak()
            except Exception:
                pass  # stub worker: no ledger to reset
            return True
        if op == "collect_handoffs":
            # Prefill role: drain the engine's outbox into the replay
            # dict, then serve EVERYTHING unacked — a coordinator whose
            # previous collect response was lost sees the same records
            # again (delivery is at-least-once; the decode side's hid
            # dedup makes the re-ship idempotent).
            fresh = (eng.collect_handoffs()
                     if hasattr(eng, "collect_handoffs") else [])
            now = time.perf_counter()
            with self._lock:
                for rec in fresh:
                    self._handoff_unacked[int(rec["rid"])] = rec
                out = []
                for rec in self._handoff_unacked.values():
                    # Refresh elapsed_s with the outbox wait at every
                    # serve (stored record untouched — replays refresh
                    # again), and keep the worker-local stamp off the
                    # wire: only durations cross processes.
                    wire = {k: v for k, v in rec.items()
                            if k != "t_gather"}
                    if rec.get("t_gather") is not None:
                        wire["elapsed_s"] = (
                            (rec.get("elapsed_s") or 0.0)
                            + (now - rec["t_gather"]))
                    out.append(wire)
                return out
        if op == "ack_handoffs":
            with self._lock:
                for rid in p["rids"]:
                    self._handoff_unacked.pop(int(rid), None)
            return True
        if op == "import_handoff":
            hid = str(p["hid"])
            with self._lock:
                if hid in self._imported:
                    return self._imported[hid]
            rid = eng.import_handoff(
                list(p["input_ids"]), int(p["max_new_tokens"]), p["rec"],
                tokens=list(p.get("tokens") or ()),
                prompt_len=int(p.get("prompt_len", 0)),
                deadline_s=p.get("deadline_s"), slo=p.get("slo"),
                elapsed_s=float(p.get("elapsed_s") or 0.0),
                ttft_s=p.get("ttft_s"))
            with self._lock:
                self._imported[hid] = rid
                while len(self._imported) > self.REPLAY_CAP:
                    self._imported.pop(next(iter(self._imported)))
            return rid
        if op == "shutdown":
            self.stop_event.set()
            return True
        raise ValueError(f"unknown rpc op {op!r}")


def _write_ready_file(path: str, port: int) -> None:
    """Atomic readiness handshake: the coordinator polls for this file
    and reads the worker's ephemeral port from it (tmp + rename, like
    the heartbeat — a half-written file is never observed)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"port": port, "pid": os.getpid()}, f)
    os.replace(tmp, path)


def serve_worker(engine, ready_file: str) -> int:
    """Run one worker's RPC server until a ``shutdown`` op (or
    SIGTERM/SIGINT) arrives; returns the process exit code. The
    engine's own heartbeat thread (``--heartbeat_dir``) keeps beating
    the whole time — that file is the coordinator's liveness signal."""
    handler = WorkerHandler(engine)
    server = rpc.RpcServer(handler)

    def _on_signal(signum, frame):
        handler.stop_event.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    _write_ready_file(ready_file, server.port)
    handler.stop_event.wait()
    # Graceful exit: settle the engine (drains the in-flight segment)
    # before the RPC server goes away, so a drain-then-shutdown
    # coordinator never races the parked scheduler.
    try:
        engine.shutdown()
    finally:
        server.stop()
    return 0


# -- the test stub (jax-free worker) ---------------------------------------

class _StubBatcher:
    """The minimal ``engine.batcher`` surface ``WorkerHandler`` reads."""

    def __init__(self):
        self.request_stats: Dict[int, dict] = {}

    def prefix_cache_stats(self) -> dict:
        return {"enabled": False}

    def reset_serving_stats(self) -> None:
        self.request_stats.clear()


class _StubEngine:
    """Deterministic jax-free fake of the ``ServingEngine`` surface the
    RPC worker exposes: request ``(ids, budget)`` "decodes" to
    ``[(sum(ids) + k) % 251 for k in range(budget)]`` after
    ``token_delay_s`` per token — the same function in every process,
    so coordinator failover tests can assert chain identity without
    paying a jax import. Used by ``--stub_worker`` mode only.

    Role support (ISSUE 17): a ``prefill`` stub "admits" a request in
    one ``token_delay_s`` and moves it to the handoff outbox with a
    deterministic ndarray "KV" payload (the input ids verbatim — it
    crosses the raw-binary RPC frame, and the decode stub REJECTS a
    corrupted array, so stub fleet tests assert bit-exact transport);
    a ``decode`` stub's ``import_handoff`` enqueues the request like a
    submit, finishing with the SAME chain function — byte-identical to
    a colocated stub run."""

    _GUARDED_BY = {"_reqs": "_lock", "_done": "_lock",
                   "_handoffs": "_lock"}

    def __init__(self, token_delay_s: float = 0.005,
                 role: str = "colocated"):
        self.token_delay_s = float(token_delay_s)
        self.role = role
        self.batcher = _StubBatcher()
        self.alive = True
        self.n_faults = 0
        self.n_restarts = 0
        self._lock = threading.Lock()
        self._next_rid = 0
        self._reqs: Dict[int, dict] = {}   # live: rid -> record
        self._done: Dict[int, tuple] = {}  # finished: rid -> (toks, st)
        self._handoffs: List[dict] = []    # prefill role: the outbox
        self.handoffs_gathered = 0
        self.handoffs_spliced = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit_ids(self, ids, pixels, max_new_tokens, stream=False,
                   deadline_s=None, slo=None) -> int:
        if not self.alive:
            raise RuntimeError("stub engine is down (killed)")
        obs_series.note_submit()
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            self._reqs[rid] = {
                "rid": rid, "ids": list(ids), "pixels": pixels,
                "budget": int(max_new_tokens), "t0": time.perf_counter(),
                "deadline_s": deadline_s, "slo": slo,
            }
        return rid

    def _chain(self, ids, budget) -> List[int]:
        s = sum(int(t) for t in ids)
        return [(s + k) % 251 for k in range(budget)]

    def _loop(self) -> None:
        while True:
            time.sleep(self.token_delay_s)
            now = time.perf_counter()
            with self._lock:
                if not self.alive:
                    continue
                for rid, r in list(self._reqs.items()):
                    if self.role == "prefill":
                        # Admission-only: one token_delay_s of "prefill"
                        # moves the request to the outbox — never a
                        # decode. The fake KV plane is the ids verbatim
                        # (int32), so the raw-frame transport is
                        # asserted bit-exact at the decode stub.
                        if now - r["t0"] < self.token_delay_s:
                            continue
                        self._reqs.pop(rid)
                        self.handoffs_gathered += 1
                        self._handoffs.append({
                            "rid": rid,
                            "input_ids": list(r["ids"]),
                            "tokens": [],
                            "max_new_tokens": r["budget"],
                            "prompt_len": len(r["ids"]),
                            "deadline_s": r["deadline_s"],
                            "slo": r["slo"],
                            "preempts": 0,
                            "journey": None,
                            "rec": {
                                "n_blocks": 1, "n_total": 1,
                                "length": len(r["ids"]),
                                "nbytes_kv": 4 * len(r["ids"]),
                                "kv": np.asarray(r["ids"], np.int32),
                            },
                        })
                        continue
                    if now - r["t0"] >= self.token_delay_s * r["budget"]:
                        self._reqs.pop(rid)
                        self._done[rid] = (
                            self._chain(r["ids"], r["budget"]), "ok")
                        self.batcher.request_stats[rid] = {
                            "latency_s": now - r["t0"], "slo_met": True}

    def collect_handoffs(self) -> List[dict]:
        with self._lock:
            out, self._handoffs = self._handoffs, []
            return out

    def import_handoff(self, input_ids, max_new_tokens, rec,
                       tokens=(), prompt_len=0, deadline_s=None,
                       slo=None, elapsed_s=0.0, ttft_s=None) -> int:
        if not self.alive:
            raise RuntimeError("stub engine is down (killed)")
        kv = rec.get("kv")
        if kv is not None and np.asarray(kv).tolist() != \
                [int(t) for t in input_ids]:
            # The transport contract IS the test: a handoff whose KV
            # plane didn't survive the raw frame bit-exact must refuse
            # the splice, not decode garbage.
            raise ValueError("stub handoff KV plane corrupted in transit")
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            self.handoffs_spliced += 1
            self._reqs[rid] = {
                "rid": rid, "ids": list(input_ids), "pixels": None,
                "budget": int(max_new_tokens), "t0": time.perf_counter(),
                "deadline_s": deadline_s, "slo": slo,
            }
        return rid

    def try_result(self, rid):
        with self._lock:
            return self._done.pop(rid, None)

    def try_status(self, rid):
        return None

    def cancel(self, rid) -> bool:
        with self._lock:
            return self._reqs.pop(rid, None) is not None

    def kill(self) -> list:
        with self._lock:
            self.alive = False
            recs = [{"rid": r["rid"], "input_ids": r["ids"],
                     "pixel_values": r["pixels"],
                     "max_new_tokens": r["budget"],
                     "deadline_s": r["deadline_s"], "slo": r["slo"]}
                    for r in self._reqs.values()]
            self._reqs.clear()
            return recs

    def breaker_open(self) -> bool:
        return not self.alive

    def goodput_ratio(self) -> float:
        return 1.0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "active_rows": len(self._reqs), "queued": 0,
                "slo": {}, "memory": {}, "role": self.role,
                # Fake pool headroom that shrinks with load, so the
                # decode-placement policy is exercised at stub speed.
                "kv_free_bytes": (1 << 20) - 4096 * len(self._reqs),
                "kv_free_blocks": 256 - len(self._reqs),
                "handoff": {
                    "pending": len(self._handoffs),
                    "gathered": self.handoffs_gathered,
                    "gathered_bytes": 0,
                    "spliced": self.handoffs_spliced,
                    "spliced_bytes": 0,
                },
            }

    def stats(self) -> dict:
        return {"stub": True, **self.snapshot()}

    def memory_stats(self) -> dict:
        return {"stub": True}

    def series(self, window_s=None, n=None) -> dict:
        # The stub worker arms a REAL store (series.py is jax-free), so
        # the procfleet aggregation tests exercise the genuine RPC +
        # merge path at stub speed.
        return obs_series.snapshot(window_s=window_s, n=n)

    def alerts(self) -> dict:
        return obs_series.alerts()

    def journey(self, rid):
        return None

    def set_prefix(self, prompt, pixels=None) -> int:
        return 0

    def shutdown(self) -> None:
        self.alive = False


def _stub_main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--stub_worker", action="store_true")
    p.add_argument("--worker_ready_file", required=True)
    p.add_argument("--worker_slot", type=int, default=0)
    p.add_argument("--heartbeat_dir", default=None)
    p.add_argument("--token_delay_s", type=float, default=0.005)
    p.add_argument("--role", default="colocated",
                   choices=("colocated", "prefill", "decode"))
    args = p.parse_args(argv)
    # A real (tiny) time-series store per stub worker: the aggregation
    # tests assert over genuine sampled rings, not canned dicts.
    obs_series.configure(interval_s=0.02, keep=256)
    engine = _StubEngine(token_delay_s=args.token_delay_s,
                         role=args.role)
    if args.heartbeat_dir:
        from eventgpt_tpu.train.resilience import Heartbeat

        hb = Heartbeat(args.heartbeat_dir)

        def _beat():
            n = 0
            while True:
                try:
                    hb.beat(n, status="ok")
                except OSError:
                    pass
                n += 1
                time.sleep(0.2)

        threading.Thread(target=_beat, daemon=True).start()
    return serve_worker(engine, args.worker_ready_file)


# -- coordinator -----------------------------------------------------------

@dataclass
class _ProcRequest:
    """One request the coordinator owns end to end (the process-fleet
    twin of ``fleet._FleetRequest``). ``worker``/``rid`` are the
    CURRENT assignment; ``t_assign`` is the coordinator-clock stamp of
    that assignment (the redo-cost anchor — worker clocks are not
    comparable across processes)."""
    frid: int
    input_ids: List[int]
    pixel_values: Any
    max_new_tokens: int
    deadline: Optional[float]          # absolute coordinator perf_counter
    slo: Any
    key: tuple
    stream: bool
    worker: int
    rid: int
    t_submit: float
    t_assign: float
    failovers: int = 0
    done: threading.Event = field(default_factory=threading.Event)
    tokens: Optional[List[int]] = None
    status: str = "ok"
    stats: Dict[str, float] = field(default_factory=dict)
    stream_q: Any = None
    # Disaggregation (ISSUE 17): the closed prefill-leg phase
    # decomposition (rides the handoff record) and the coordinator-
    # measured collect->import wall time — both stitched into the final
    # journey. Reset on failover: a REDO restarts the whole chain, and
    # only the FINAL chain's legs may sum into the timeline.
    prefill_phases: Optional[Dict[str, float]] = None
    handoff_s: float = 0.0


@dataclass
class WorkerSlot:
    """One supervised worker-process slot. ``state`` drives
    routability: only ``ok`` slots receive work. Single-writer from
    the supervisor thread in steady state (the documented Replica
    exception from PR 7/8 — operator kill/drain transitions are
    idempotent); cross-object fields are outside the lock detector's
    static scope either way."""
    idx: int
    proc: Optional[subprocess.Popen] = None
    addr: Optional[Tuple[str, int]] = None
    # colocated | prefill | decode (fixed at fleet construction: a
    # slot's role survives respawn — the topology is static)
    role: str = "colocated"
    # starting | ok | suspect | draining | dead | failed
    state: str = "starting"
    generation: int = 0                # spawn attempts (ready-file key)
    t_spawn: float = 0.0               # monotonic spawn start
    t_dead: float = 0.0
    t_respawn: float = 0.0             # monotonic: respawn allowed after
    crashes: List[float] = field(default_factory=list)  # monotonic stamps
    consec_crashes: int = 0
    kills: int = 0                     # operator/chaos kills + drains
    inflight: int = 0                  # coordinator-side assigned count
    snapshot: Dict[str, Any] = field(default_factory=dict)
    ready_file: str = ""
    hb_dir: Optional[str] = None
    log_path: str = ""
    respawn_frids: List[int] = field(default_factory=list)

    @property
    def routable(self) -> bool:
        return self.state == "ok"


class _ProcRequestStats:
    """``.get(frid)`` view over finished requests — the shape
    ``make_handler`` expects of ``engine.batcher.request_stats``."""

    def __init__(self, fleet: "ProcFleet"):
        self._fleet = fleet

    def get(self, frid: int, default=None):
        freq = self._fleet._requests.get(frid)
        if freq is None or not freq.done.is_set():
            return default if default is not None else {}
        return freq.stats


class _ProcBatcherView:
    """The minimal ``engine.batcher`` surface the HTTP handler reads,
    aggregated across worker snapshots (one RPC-free read: the
    supervisor refreshes snapshots every probe tick)."""

    def __init__(self, fleet: "ProcFleet"):
        self._fleet = fleet
        self.request_stats = _ProcRequestStats(fleet)

    def prefix_cache_stats(self) -> Dict[str, Any]:
        per = []
        hits = misses = 0
        for slot in self._fleet.slots:
            st = dict(slot.snapshot.get("prefix_cache", {}))
            per.append({"worker": slot.idx, **st})
            hits += st.get("hits", 0)
            misses += st.get("misses", 0)
        return {
            "enabled": any(p.get("enabled") for p in per),
            "hits": hits,
            "misses": misses,
            "hit_ratio": hits / (hits + misses) if (hits + misses) else 0.0,
            "workers": per,
        }

    def slo_stats(self) -> Dict[str, Any]:
        return self._fleet.slo_stats()


class ProcFleet:
    """Coordinator over N worker processes with the client surface of
    a ``ServingEngine`` (submit / result / status / cancel /
    stream_queue / stats / breaker_open / set_prefix), so
    ``cli.serve.make_handler`` serves a process fleet unchanged. See
    the module docstring for the robustness layers.

    Lock discipline (egpt-check rule ``lock``): same contract as
    ``Fleet`` — the routing table and request-map WRITES mutate under
    ``_lock``; ``/w`` attributes are read lock-free by design
    (``result`` must not hold the lock while waiting). RPC submits
    happen under the lock (the fleet -> worker "lock order": workers
    never call back into the coordinator, so it cannot invert);
    collection/probe RPCs run outside it. ``WorkerSlot`` fields are
    the documented single-writer exception (supervisor thread), like
    ``fleet.Replica.state``."""

    _GUARDED_BY = {
        # full guard: routing/bookkeeping state with compound updates
        "_pins": "_lock",
        "_next_frid": "_lock",
        # writes locked; lock-free reads are the snapshot/flag pattern
        "_requests": "_lock/w",
        "n_requests": "_lock/w",
        "n_failovers": "_lock/w",
        "n_deaths": "_lock/w",
        "n_respawns": "_lock/w",
        "n_kills": "_lock/w",
        "n_crash_looped": "_lock/w",
        "n_handoffs": "_lock/w",
        "n_handoff_bytes": "_lock/w",
        "n_handoff_retries": "_lock/w",
        "n_handoff_redos": "_lock/w",
        "fault": "_lock/w",
    }

    def __init__(self, worker_cmd: Sequence[str], n_workers: int,
                 tokenizer=None, conv_mode: str = "eventgpt_v1",
                 workdir: Optional[str] = None,
                 heartbeat_dir: Optional[str] = None,
                 probe_interval_s: float = 0.05,
                 heartbeat_stale_s: float = 5.0,
                 rpc_deadline_s: float = 15.0,
                 rpc_retries: int = 3,
                 drain_deadline_s: float = 30.0,
                 spawn_timeout_s: float = 120.0,
                 respawn_backoff_s: float = 0.25,
                 respawn_backoff_max_s: float = 10.0,
                 crash_window_s: float = 60.0,
                 crash_limit: int = 3,
                 max_failovers: int = 3,
                 shutdown_drain_s: float = 30.0,
                 roles: Optional[str] = None,
                 handoff_retries: int = 3):
        if n_workers < 1:
            raise ValueError("a process fleet needs at least one worker")
        # Disaggregated topology (ISSUE 17): "P:D" fixes the first P
        # slots as prefill workers, the rest as decode. None keeps
        # every slot colocated — the default topology, byte-for-byte
        # the pre-disaggregation fleet.
        self.roles: Optional[Tuple[int, int]] = None
        if roles:
            p_str, sep, d_str = str(roles).partition(":")
            try:
                if not sep:
                    raise ValueError(roles)
                n_p, n_d = int(p_str), int(d_str)
            except ValueError:
                raise ValueError(
                    f"bad proc_fleet_roles {roles!r} (want P:D, e.g. 1:1)")
            if n_p < 1 or n_d < 1:
                raise ValueError(
                    f"proc_fleet_roles {roles!r}: a disaggregated fleet "
                    f"needs at least one prefill AND one decode worker")
            if n_p + n_d != n_workers:
                raise ValueError(
                    f"proc_fleet_roles {roles!r}: {n_p}+{n_d} workers "
                    f"!= fleet size {n_workers}")
            self.roles = (n_p, n_d)
        self.handoff_retries = int(handoff_retries)
        self.worker_cmd = list(worker_cmd)
        self.tokenizer = tokenizer
        self.conv_mode = conv_mode
        self.probe_interval_s = float(probe_interval_s)
        self.heartbeat_stale_s = float(heartbeat_stale_s)
        self.rpc_deadline_s = float(rpc_deadline_s)
        self.rpc_retries = int(rpc_retries)
        self.drain_deadline_s = float(drain_deadline_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.respawn_backoff_max_s = float(respawn_backoff_max_s)
        self.crash_window_s = float(crash_window_s)
        self.crash_limit = int(crash_limit)
        self.max_failovers = int(max_failovers)
        self.shutdown_drain_s = float(shutdown_drain_s)
        if workdir is None:
            import tempfile

            self._tmpdir = tempfile.TemporaryDirectory(
                prefix="egpt_procfleet_")
            workdir = self._tmpdir.name
        else:
            self._tmpdir = None
            os.makedirs(workdir, exist_ok=True)
        self.workdir = workdir
        self.heartbeat_root = heartbeat_dir
        self._lock = threading.Lock()
        self._requests: Dict[int, _ProcRequest] = {}
        self._pins: Dict[tuple, int] = {}
        self._next_frid = 0
        self._stop = False
        self.t_start = time.time()
        self.n_requests = 0
        self.n_failovers = 0
        self.n_deaths = 0
        self.n_respawns = 0
        self.n_kills = 0
        self.n_crash_looped = 0
        self.n_handoffs = 0
        self.n_handoff_bytes = 0
        self.n_handoff_retries = 0
        self.n_handoff_redos = 0
        # Serializes collect->ship->ack per pump pass: the supervisor's
        # periodic pump and a drain's flush pump must not ship the same
        # replayed record concurrently (the hid dedup would still
        # prevent a double splice, but the bookkeeping would race).
        self._pump_lock = threading.Lock()
        self.fault: Any = None
        self._journey_owner = obs_journey.register_owner("procfleet")
        self.slots = [self._make_slot(i) for i in range(n_workers)]
        obs_metrics.PROCFLEET_WORKERS.set(n_workers)
        for slot in self.slots:
            self._spawn(slot)
        self._wait_boot()
        self._thread = threading.Thread(target=self._supervise, daemon=True)
        self._thread.start()

    # -- spawning ----------------------------------------------------------

    def _make_slot(self, idx: int) -> WorkerSlot:
        hb = (os.path.join(self.heartbeat_root, f"replica{idx}")
              if self.heartbeat_root else None)
        role = "colocated"
        if self.roles is not None:
            role = "prefill" if idx < self.roles[0] else "decode"
        return WorkerSlot(idx=idx, hb_dir=hb, role=role,
                          log_path=os.path.join(self.workdir,
                                                f"worker{idx}.log"))

    def _spawn(self, slot: WorkerSlot) -> bool:
        """Launch one worker process into ``slot`` (state ->
        ``starting``; readiness is polled by the supervisor). A
        ``procfleet.spawn`` trip fails THIS attempt — it is booked as a
        crash so the backoff/breaker policy governs retries, exactly
        like a real exec failure."""
        slot.generation += 1
        slot.ready_file = os.path.join(
            self.workdir, f"worker{slot.idx}.g{slot.generation}.ready")
        cmd = self.worker_cmd + [
            "--worker_ready_file", slot.ready_file,
            "--worker_slot", str(slot.idx),
        ]
        if slot.role != "colocated":
            cmd += ["--role", slot.role]
        if slot.hb_dir:
            cmd += ["--heartbeat_dir", slot.hb_dir]
        try:
            faults.maybe_fail("procfleet.spawn")
            faults.maybe_delay("procfleet.spawn")
            log = open(slot.log_path, "ab")
            try:
                slot.proc = subprocess.Popen(
                    cmd, stdout=log, stderr=subprocess.STDOUT,
                    cwd=os.getcwd())
            finally:
                log.close()
        except (faults.InjectedFault, OSError) as e:
            slot.proc = None
            self._book_crash(slot, f"spawn failed: {e!r}")
            return False
        slot.state = "starting"
        slot.t_spawn = time.monotonic()
        slot.addr = None
        obs_trace.instant("worker_spawn", cat="procfleet")
        return True

    def _wait_boot(self) -> None:
        """Block until every slot left ``starting`` (ready, crashed, or
        spawn-timeout) — at least one must be routable or the fleet
        cannot exist."""
        deadline = time.monotonic() + self.spawn_timeout_s
        while time.monotonic() < deadline:
            for slot in self.slots:
                if slot.state == "starting":
                    self._check_ready(slot)
                elif slot.state == "dead" \
                        and time.monotonic() >= slot.t_respawn:
                    self._maybe_respawn(slot)
            if all(s.state in ("ok", "failed") for s in self.slots):
                break
            time.sleep(0.02)
        self._export_routable_gauge()
        if not any(s.routable for s in self.slots):
            states = {s.idx: s.state for s in self.slots}
            self.shutdown()
            raise RuntimeError(
                f"no worker became routable within {self.spawn_timeout_s}s "
                f"(states: {states}; logs under {self.workdir})")

    def _check_ready(self, slot: WorkerSlot) -> None:
        """Advance a ``starting`` slot: ready file -> addr -> ok; a
        dead process or an expired spawn deadline books a crash."""
        if slot.proc is not None and slot.proc.poll() is not None:
            self._book_crash(
                slot, f"worker {slot.idx} exited rc={slot.proc.returncode} "
                      f"during startup (log: {slot.log_path})")
            return
        if os.path.exists(slot.ready_file):
            try:
                with open(slot.ready_file) as f:
                    info = json.load(f)
                slot.addr = ("127.0.0.1", int(info["port"]))
                self._rpc(slot, "ping", deadline_s=5.0)
            except (OSError, ValueError, KeyError, rpc.RpcError):
                return  # not answering yet: keep polling
            slot.state = "ok"
            slot.consec_crashes = 0
            self._export_routable_gauge()
            return
        if time.monotonic() - slot.t_spawn > self.spawn_timeout_s:
            self._kill_proc(slot)
            self._book_crash(
                slot, f"worker {slot.idx} never became ready within "
                      f"{self.spawn_timeout_s}s")

    def _book_crash(self, slot: WorkerSlot, why: str) -> None:
        """Crash bookkeeping + the crash-loop breaker (robustness layer
        3): K crashes inside the window -> give the slot up for good —
        capacity degrades, the fleet stays up on the others."""
        now = time.monotonic()
        slot.proc = None
        slot.addr = None
        slot.t_dead = now
        slot.crashes.append(now)
        slot.crashes = [t for t in slot.crashes
                        if now - t <= self.crash_window_s]
        slot.consec_crashes += 1
        with self._lock:
            self.fault = why
        if len(slot.crashes) >= self.crash_limit:
            slot.state = "failed"
            with self._lock:
                self.n_crash_looped += 1
            obs_metrics.PROCFLEET_CRASH_LOOPS.inc()
            obs_trace.instant("worker_crash_loop", cat="procfleet")
        else:
            slot.state = "dead"
            backoff = min(
                self.respawn_backoff_s
                * (2.0 ** max(slot.consec_crashes - 1, 0)),
                self.respawn_backoff_max_s)
            slot.t_respawn = now + backoff
        self._export_routable_gauge()

    def _maybe_respawn(self, slot: WorkerSlot) -> None:
        if slot.state != "dead" or time.monotonic() < slot.t_respawn:
            return
        if self._spawn(slot):
            with self._lock:
                self.n_respawns += 1
            obs_metrics.PROCFLEET_RESPAWNS.inc()
            # The respawn is part of the affected requests' story: any
            # request this slot's death re-routed that is STILL live
            # gets the respawn event (the chaos test asserts the
            # worker_lost -> failover -> respawn sequence).
            frids, slot.respawn_frids = slot.respawn_frids, []
            for frid in frids:
                freq = self._requests.get(frid)
                if freq is not None and not freq.done.is_set():
                    obs_journey.event(self._journey_owner, frid,
                                      "respawn", worker=slot.idx)

    def _kill_proc(self, slot: WorkerSlot) -> None:
        if slot.proc is None:
            return
        try:
            slot.proc.kill()
            slot.proc.wait(timeout=5)
        except (OSError, subprocess.TimeoutExpired):
            pass

    # -- rpc helper --------------------------------------------------------

    def _rpc(self, slot: WorkerSlot, op: str,
             payload: Optional[dict] = None, *,
             deadline_s: Optional[float] = None,
             retry_sent: bool = True) -> Any:
        if slot.addr is None:
            raise rpc.RpcError(f"worker {slot.idx} has no address "
                               f"(state {slot.state})")
        return rpc.call(slot.addr, op, payload,
                        deadline_s=(self.rpc_deadline_s
                                    if deadline_s is None else deadline_s),
                        retries=self.rpc_retries, retry_sent=retry_sent)

    # -- client surface ----------------------------------------------------

    @property
    def batcher(self) -> _ProcBatcherView:
        return _ProcBatcherView(self)

    @property
    def n_faults(self) -> int:
        return sum(s.snapshot.get("n_faults", 0) for s in self.slots)

    @property
    def n_restarts(self) -> int:
        return sum(s.snapshot.get("n_restarts", 0) for s in self.slots)

    def breaker_open(self) -> bool:
        """The fleet refuses work only when NO worker is routable —
        one healthy worker keeps /health green (lost capacity shows in
        egpt_procfleet_workers_routable instead). A disaggregated
        fleet needs one routable worker of EACH role: a prefill-only
        fleet can admit but never decode, a decode-only fleet can
        never admit."""
        if self.roles is not None:
            return not (
                any(s.routable and s.role == "prefill"
                    for s in self.slots)
                and any(s.routable and s.role == "decode"
                        for s in self.slots))
        return not any(s.routable for s in self.slots)

    def goodput_ratio(self) -> float:
        met = 0.0
        n = 0
        for slot in self.slots:
            st = slot.snapshot.get("slo", {})
            w = st.get("window_n", 0)
            met += st.get("goodput_ratio", 0.0) * w
            n += w
        return met / n if n else 1.0

    def queue_depth(self) -> int:
        return sum(s.snapshot.get("queued", 0) for s in self.slots)

    def submit(self, query: str, pixels, max_new_tokens: int,
               stream: bool = False, deadline_s: Optional[float] = None,
               slo=None) -> int:
        from eventgpt_tpu.data.conversation import prepare_event_prompt
        from eventgpt_tpu.data.tokenizer import tokenize_with_event

        ids = tokenize_with_event(
            prepare_event_prompt(query, self.conv_mode), self.tokenizer)
        return self.submit_ids(ids, pixels, max_new_tokens, stream=stream,
                               deadline_s=deadline_s, slo=slo)

    def submit_ids(self, input_ids: Sequence[int], pixels,
                   max_new_tokens: int, stream: bool = False,
                   deadline_s: Optional[float] = None, slo=None) -> int:
        """Route one request: affinity -> least-inflight, submit over
        RPC (non-idempotent: never retried after the bytes left — an
        unreachable worker is marked suspect and the NEXT candidate is
        tried instead, so transport trouble costs locality, not
        availability), track for supervision."""
        # Coordinator-side arrival sensing (ISSUE 15): workers only see
        # their routed share, so the fleet-wide EWMA lives here.
        obs_series.note_submit()
        key = affinity_key(input_ids, pixels)
        with self._lock:
            last_err: Optional[Exception] = None
            tried: set = set()
            while True:
                slot, reason = self._route_locked(key, exclude=tried)
                try:
                    rid = self._rpc(
                        slot, "submit_ids",
                        {"input_ids": list(input_ids),
                         "pixel_values": pixels,
                         "max_new_tokens": int(max_new_tokens),
                         "deadline_s": deadline_s, "slo": slo},
                        retry_sent=False)
                    break
                except rpc.RpcRemoteError as e:
                    raise _map_remote(e) from e
                except rpc.RpcError as e:
                    # Transport failure: this worker is suspect (the
                    # supervisor's probe will drain or declare it) —
                    # try the next candidate rather than failing the
                    # client while capacity remains.
                    last_err = e
                    tried.add(slot.idx)
                    slot.state = "suspect"
                    self._export_routable_gauge()
                    if not any(s.routable for s in self.slots):
                        raise RuntimeError(
                            f"no routable worker accepted the submit: "
                            f"{last_err!r}") from e
            t = time.perf_counter()
            frid = self._next_frid
            self._next_frid += 1
            freq = _ProcRequest(
                frid=frid, input_ids=list(input_ids), pixel_values=pixels,
                max_new_tokens=int(max_new_tokens),
                deadline=(t + deadline_s if deadline_s is not None
                          else None),
                slo=slo, key=key, stream=stream, worker=slot.idx, rid=rid,
                t_submit=t, t_assign=t)
            if stream:
                import queue as _queue

                freq.stream_q = _queue.Queue()
            self._requests[frid] = freq
            self._pins[key] = slot.idx
            self.n_requests += 1
            slot.inflight += 1
            obs_metrics.FLEET_ROUTED.inc(reason=reason)
            obs_journey.begin(
                self._journey_owner, frid, t=t, budget=max_new_tokens,
                **({"slo_class": slo.name} if slo is not None else {}))
            obs_journey.event(self._journey_owner, frid, "route", t=t,
                              worker=slot.idx, worker_rid=rid,
                              reason=reason)
        return frid

    def result(self, frid: int, timeout: float = 600.0) -> List[int]:
        freq = self._requests[frid]
        if not freq.done.wait(timeout):
            raise TimeoutError(
                f"procfleet request {frid} did not finish in {timeout}s")
        if freq.tokens is None:
            raise RuntimeError(
                f"procfleet request {frid} failed after {freq.failovers} "
                f"failover(s): {freq.status} ({self.fault})")
        return freq.tokens

    def status(self, frid: int) -> str:
        freq = self._requests.get(frid)
        return freq.status if freq is not None else "ok"

    def worker_of(self, frid: int) -> int:
        return self._requests[frid].worker

    # bench/test shared-code alias (the thread fleet calls it replica_of)
    replica_of = worker_of

    def cancel(self, frid: int) -> bool:
        with self._lock:
            freq = self._requests.get(frid)
            if freq is None or freq.done.is_set():
                return False
            slot = self.slots[freq.worker]
        try:
            return bool(self._rpc(slot, "cancel", {"rid": freq.rid},
                                  deadline_s=5.0))
        except rpc.RpcError:
            return False

    def stream_queue(self, frid: int):
        return self._requests[frid].stream_q

    def set_prefix(self, prefix_prompt: str, pixels=None) -> int:
        """Broadcast the operator prefix insert to every routable
        worker (the fleet-wide POST /prefix contract)."""
        plen = 0
        for slot in self.slots:
            if not slot.routable:
                continue
            try:
                plen = int(self._rpc(slot, "set_prefix",
                                     {"prefix_prompt": prefix_prompt,
                                      "pixel_values": pixels}))
            except rpc.RpcError:
                continue
        return plen

    def slo_stats(self) -> Dict[str, Any]:
        classes: Dict[str, Dict[str, int]] = {}
        for slot in self.slots:
            st = slot.snapshot.get("slo", {})
            for name, c in st.get("classes", {}).items():
                agg = classes.setdefault(name, {"finished": 0, "met": 0})
                agg["finished"] += c["finished"]
                agg["met"] += c["met"]
        for c in classes.values():
            c["attainment"] = (c["met"] / c["finished"]
                               if c["finished"] else 0.0)
        return {"classes": classes, "goodput_ratio": self.goodput_ratio()}

    def stats(self) -> Dict[str, Any]:
        per = []
        for slot in self.slots:
            s = slot.snapshot
            per.append({
                "worker": slot.idx,
                "state": slot.state,
                "role": slot.role,
                "pid": slot.proc.pid if slot.proc else None,
                "active_rows": s.get("active_rows", 0),
                "queued": s.get("queued", 0),
                "inflight": slot.inflight,
                # Disaggregation surface (ISSUE 17): block-pool
                # headroom (the decode-placement signal) and the
                # worker-side handoff counters from the last probe.
                "kv_free_blocks": s.get("kv_free_blocks"),
                "kv_free_bytes": s.get("kv_free_bytes"),
                "handoff": s.get("handoff") or {},
                "faults": s.get("n_faults", 0),
                "restarts": s.get("n_restarts", 0),
                "crashes": len(slot.crashes),
                "kills": slot.kills,
                "goodput_ratio": s.get("slo", {}).get(
                    "goodput_ratio", 0.0),
                "prefix_cache_hit_ratio": s.get("prefix_cache", {}).get(
                    "hit_ratio", 0.0),
                # Per-worker component bytes (each worker is its OWN
                # process: its ledger covers its weights + caches —
                # nothing is shared across the boundary).
                "memory_bytes": sum(
                    s.get("memory", {}).get("owner", {}).values()),
            })
        with self._lock:
            n_pins = len(self._pins)
        return {
            "uptime_s": round(time.time() - self.t_start, 1),
            "requests": self.n_requests,
            "status": "degraded" if self.breaker_open() else "ok",
            "active_rows": sum(p["active_rows"] for p in per),
            "queued": sum(p["queued"] for p in per),
            "fleet": {
                "proc_fleet": True,
                "workers": len(self.slots),
                "routable": sum(s.routable for s in self.slots),
                "pins": n_pins,
                "goodput_ratio": round(self.goodput_ratio(), 4),
                "failovers": self.n_failovers,
                "deaths": self.n_deaths,
                "respawns": self.n_respawns,
                "kills": self.n_kills,
                "crash_looped": self.n_crash_looped,
                # Role topology + handoff totals (ISSUE 17): None/0s
                # on a colocated fleet — the shape is stable so /fleet
                # consumers need no feature detection.
                "roles": (f"{self.roles[0]}:{self.roles[1]}"
                          if self.roles is not None else None),
                "handoffs": {
                    "shipped": self.n_handoffs,
                    "bytes": self.n_handoff_bytes,
                    "retries": self.n_handoff_retries,
                    "redos": self.n_handoff_redos,
                    "gathered": sum(
                        (p["handoff"] or {}).get("gathered", 0)
                        for p in per),
                    "spliced": sum(
                        (p["handoff"] or {}).get("spliced", 0)
                        for p in per),
                    "pending": sum(
                        (p["handoff"] or {}).get("pending", 0)
                        for p in per),
                },
                "per_worker": per,
            },
            "metrics": obs_metrics.REGISTRY.summary(
                ("egpt_serve_", "egpt_procfleet_")),
            # Unlike the thread fleet there is no process-global ledger
            # to report: each worker accounts its own bytes, summarized
            # per worker above (GET /memory fetches the full ledgers).
            "memory": {"per_worker": [
                {"worker": p["worker"], "memory_bytes": p["memory_bytes"]}
                for p in per]},
            # Coordinator store state + each worker's active rules from
            # the cached probe snapshots (ISSUE 15) — no RPC fan-out on
            # the stats poll; GET /alerts pulls the full worker logs.
            "alerts": {
                **obs_series.alert_stats(),
                "workers_active": sorted({
                    r for slot in self.slots
                    for r in slot.snapshot.get("alerts_active", [])}),
            },
        }

    def fleet_stats(self) -> Dict[str, Any]:
        """The /fleet route body (topology + policy + live state)."""
        return {
            **self.stats()["fleet"],
            "policy": {
                "probe_interval_s": self.probe_interval_s,
                "heartbeat_stale_s": self.heartbeat_stale_s,
                "rpc_deadline_s": self.rpc_deadline_s,
                "rpc_retries": self.rpc_retries,
                "respawn_backoff_s": self.respawn_backoff_s,
                "respawn_backoff_max_s": self.respawn_backoff_max_s,
                "crash_window_s": self.crash_window_s,
                "crash_limit": self.crash_limit,
                "max_failovers": self.max_failovers,
                "handoff_retries": self.handoff_retries,
            },
        }

    def memory_stats(self) -> Dict[str, Any]:
        """``GET /memory``, process-fleet form: each worker's OWN
        ledger + reconciliation, fetched over RPC (per-worker component
        bytes — the ISSUE 11 memory-plumbing satellite). Workers that
        do not answer inside the probe deadline report an error entry
        instead of stalling the route."""
        out = []
        for slot in self.slots:
            if slot.addr is None:
                out.append({"worker": slot.idx, "state": slot.state})
                continue
            try:
                out.append({"worker": slot.idx, "state": slot.state,
                            **self._rpc(slot, "memory",
                                        deadline_s=10.0)})
            except rpc.RpcError as e:
                out.append({"worker": slot.idx, "state": slot.state,
                            "error": repr(e)})
        return {"proc_fleet": True, "workers": out}

    def series(self, window_s: Optional[float] = None,
               n: Optional[int] = None) -> Dict[str, Any]:
        """``GET /series``, process-fleet form (ISSUE 15): each
        worker's OWN sampled ring + derivations, fetched over RPC, plus
        the coordinator's store. Every export is duration-aligned
        (ages relative to each store's own now) — worker perf_counter
        clocks are not comparable across processes, ages are. A worker
        that does not answer inside the deadline reports an error entry
        instead of stalling the route (the /memory contract)."""
        workers = []
        for slot in self.slots:
            if slot.addr is None:
                workers.append({"worker": slot.idx, "state": slot.state})
                continue
            try:
                workers.append({"worker": slot.idx, "state": slot.state,
                                **self._rpc(slot, "series",
                                            {"window_s": window_s, "n": n},
                                            deadline_s=10.0)})
            except rpc.RpcError as e:
                workers.append({"worker": slot.idx, "state": slot.state,
                                "error": repr(e)})
        # Fleet-wide aggregate over the answering workers: rates sum,
        # depths sum, attainment floors take the worst replica.
        agg: Dict[str, float] = {}
        for w in workers:
            d = w.get("derived") or {}
            for key in ("request_rate_per_s", "token_rate_per_s",
                        "submit_rate_per_s", "queue_depth_last"):
                if key in d:
                    agg[key] = round(agg.get(key, 0.0) + d[key], 6)
            for key in ("goodput_ratio_min", "attainment_windowed"):
                if key in d:
                    agg[key] = min(agg.get(key, 1.0), d[key])
        return {
            "proc_fleet": True,
            "coordinator": obs_series.snapshot(window_s=window_s, n=n),
            "workers": workers,
            "aggregate": agg,
        }

    def alerts(self) -> Dict[str, Any]:
        """``GET /alerts``, process-fleet form: the coordinator's rule
        state + each worker's, pulled over RPC (error entries for
        non-answering workers, like /series)."""
        workers = []
        for slot in self.slots:
            if slot.addr is None:
                workers.append({"worker": slot.idx, "state": slot.state})
                continue
            try:
                workers.append({"worker": slot.idx, "state": slot.state,
                                **self._rpc(slot, "alerts",
                                            deadline_s=10.0)})
            except rpc.RpcError as e:
                workers.append({"worker": slot.idx, "state": slot.state,
                                "error": repr(e)})
        return {
            "proc_fleet": True,
            "coordinator": obs_series.alerts(),
            "workers": workers,
            "active": sorted({r for w in workers
                              for r in w.get("active", [])}),
        }

    def reset_stats(self, clear_prefix_cache: bool = False) -> None:
        """Zero the phase-scoped counters here and in every worker
        (the bench's per-point reset)."""
        with self._lock:
            self.n_failovers = 0
            self.n_deaths = 0
            self.n_respawns = 0
            self.n_kills = 0
            self.n_handoffs = 0
            self.n_handoff_bytes = 0
            self.n_handoff_retries = 0
            self.n_handoff_redos = 0
        for slot in self.slots:
            if not slot.routable:
                continue
            try:
                self._rpc(slot, "reset_stats",
                          {"clear_prefix_cache": clear_prefix_cache},
                          deadline_s=10.0)
            except rpc.RpcError:
                continue

    def journey(self, frid: int) -> Optional[Dict[str, Any]]:
        """Coordinator timeline (route / worker_lost / failover / repin
        / respawn) with each assignment's worker timeline attached over
        RPC, plus the stitched decomposition stored at finish."""
        rec = obs_journey.get(self._journey_owner, frid)
        if rec is None:
            return None
        legs = []
        for w_idx, rid in self._assignments_of(rec["events"]):
            jr = None
            if w_idx is not None and rid is not None \
                    and 0 <= w_idx < len(self.slots) \
                    and self.slots[w_idx].addr is not None:
                try:
                    jr = self._rpc(self.slots[w_idx], "journey",
                                   {"rid": rid}, deadline_s=5.0)
                except rpc.RpcError:
                    jr = None
            legs.append({"worker": w_idx, "rid": rid, "journey": jr})
        rec["assignments"] = legs
        return rec

    def journeys(self, n: int = 64) -> List[Dict[str, Any]]:
        return obs_journey.index(self._journey_owner, n)

    @staticmethod
    def _assignments_of(events) -> List[tuple]:
        out = []
        for ev in events:
            if ev.get("kind") == "route":
                out.append((ev.get("worker"), ev.get("worker_rid")))
            elif ev.get("kind") == "failover":
                out.append((ev.get("to_worker"), ev.get("worker_rid")))
            elif (ev.get("kind") == "kv_handoff"
                    and ev.get("stage") == "shipped"):
                # The decode leg of a disaggregated request is a real
                # assignment: its worker holds the continued timeline.
                out.append((ev.get("to_worker"), ev.get("worker_rid")))
        return out

    # -- routing -----------------------------------------------------------

    def _route_locked(self, key: tuple, exclude=()) -> tuple:
        """(slot, reason): the key's pinned worker while routable, else
        least coordinator-tracked inflight (snapshot queue depths lag a
        probe tick; the coordinator's own assignment count does not).
        Disaggregated fleets route new submissions to the PREFILL pool
        only — prefix affinity keys prefill placement, where the radix
        caches actually serve prompt heads."""
        pool = [s for s in self.slots
                if s.routable and s.idx not in exclude
                and (self.roles is None or s.role == "prefill")]
        if not pool:
            raise RuntimeError(
                f"no routable{' prefill' if self.roles else ''} worker "
                f"({len(self.slots)} slot(s)): {self.fault}")
        pinned = self._pins.get(key)
        if pinned is not None and pinned not in exclude \
                and self.slots[pinned].routable \
                and (self.roles is None
                     or self.slots[pinned].role == "prefill"):
            return self.slots[pinned], "affinity"
        return (min(pool, key=lambda s: (s.inflight, s.idx)),
                "least_queue")

    def _route_decode_locked(self, exclude=()) -> Optional[WorkerSlot]:
        """Decode placement balances BLOCK-POOL HEADROOM, not queue
        depth: the splice must re-allocate the request's full paged
        reservation, so the worker with the most free KV bytes (from
        its last probe snapshot; coordinator-tracked inflight breaks
        ties) takes the next handoff. None when no decode worker is
        currently routable — the caller keeps the record replayable."""
        pool = [s for s in self.slots
                if s.routable and s.role == "decode"
                and s.idx not in exclude]
        if not pool:
            return None

        def headroom(s: WorkerSlot):
            snap = s.snapshot or {}
            return (snap.get("kv_free_bytes")
                    or snap.get("kv_free_blocks") or 0)

        return min(pool, key=lambda s: (-headroom(s), s.inflight, s.idx))

    # -- supervision -------------------------------------------------------

    def kill_worker(self, idx: int) -> None:
        """Operator/chaos hard kill: SIGKILL the worker process NOW.
        The supervisor's next pass observes the exit and runs the REDO
        failover (no drain possible — the process is gone)."""
        slot = self.slots[idx]
        if slot.proc is None:
            return
        slot.kills += 1
        with self._lock:
            self.n_kills += 1
        try:
            slot.proc.kill()
        except OSError:
            pass
        obs_trace.instant("worker_kill", cat="procfleet")

    def drain_worker(self, idx: int) -> int:
        """Operator graceful drain: export the worker's unfinished
        requests over RPC and re-route them (committed tokens
        discarded — chains stay byte-identical), collect anything it
        already finished, then shut the process down. Returns the
        number of re-routed requests. The slot respawns per the normal
        backoff policy (a drain is a kill, not a crash)."""
        slot = self.slots[idx]
        if slot.state in ("dead", "failed") or slot.addr is None:
            return 0
        slot.state = "draining"
        slot.kills += 1
        with self._lock:
            self.n_kills += 1
        self._export_routable_gauge()
        if slot.role == "prefill":
            # Flush the handoff outbox BEFORE the export: gathered
            # records are neither queued nor in-flight on this worker
            # any more (the gather tore the row down), so the export
            # would miss them and their KV would die with the process.
            self._pump_slot_handoffs(slot)
        try:
            exported = self._rpc(slot, "export_requests",
                                 deadline_s=self.drain_deadline_s)
        except rpc.RpcError:
            # It stopped answering mid-drain: hard loss, redo path.
            self._kill_proc(slot)
            self._on_worker_lost(slot, f"worker {idx} unreachable "
                                       f"during drain", graceful=False)
            return 0
        if slot.role == "prefill":
            # Once more after the export parked the scheduler: a row
            # gathered between the first flush and the park would
            # otherwise strand. Nothing can gather after this (the
            # engine is parked), so the outbox is now final.
            self._pump_slot_handoffs(slot)
            # Anything STILL unacked could not ship (e.g. no decode
            # worker routable right now). Its KV dies with this
            # process — REDO each owner from the coordinator record
            # rather than stranding it behind the graceful-drain
            # "finished but uncollected" skip below.
            try:
                left = self._rpc(slot, "collect_handoffs",
                                 deadline_s=10.0)
            except rpc.RpcError:
                left = []
            with self._lock:
                for out in left or []:
                    freq = next(
                        (f for f in self._requests.values()
                         if f.worker == slot.idx
                         and f.rid == int(out["rid"])
                         and not f.done.is_set()), None)
                    if freq is None:
                        continue
                    remaining = (freq.deadline - time.perf_counter()
                                 if freq.deadline is not None else None)
                    self._failover_locked(freq, remaining, "redo")
        moved = self._on_worker_lost(
            slot, f"worker {idx} drained", graceful=True,
            exported=exported or [])
        # Collect finished-but-uncollected answers while the parked
        # worker still answers, then take the process down cleanly.
        self._collect()
        try:
            self._rpc(slot, "shutdown", deadline_s=5.0)
        except rpc.RpcError:
            pass
        if slot.proc is not None:
            try:
                slot.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._kill_proc(slot)
        now = time.monotonic()
        slot.proc = None
        slot.addr = None
        slot.state = "dead"
        slot.t_dead = now
        slot.t_respawn = now + self.respawn_backoff_s
        self._export_routable_gauge()
        return moved

    def _on_worker_lost(self, slot: WorkerSlot, why: str,
                        graceful: bool, exported=None) -> int:
        """Fail over a lost worker's live requests. Graceful: exported
        records re-submit with their remaining deadline headroom
        (path=drain). Hard: the coordinator re-submits from its OWN
        records (path=redo) and stamps ``worker_lost`` on each victim's
        timeline. Returns the number of moved requests."""
        path = "drain" if graceful else "redo"
        with self._lock:
            self.n_deaths += 1
            self.fault = why
        obs_metrics.PROCFLEET_WORKER_DEATHS.inc()
        obs_trace.instant("worker_lost", cat="procfleet", why=why)
        by_rid = {rec["rid"]: rec for rec in (exported or [])}
        moved = 0
        with self._lock:
            victims = [f for f in self._requests.values()
                       if f.worker == slot.idx and not f.done.is_set()]
            for freq in victims:
                if graceful and freq.rid not in by_rid:
                    # Finished at the worker but uncollected: the drain
                    # sequence's collect pass (worker still answering)
                    # delivers it — leave it tracked.
                    continue
                if not graceful:
                    obs_journey.event(self._journey_owner, freq.frid,
                                      "worker_lost", worker=slot.idx)
                rec = by_rid.get(freq.rid)
                deadline_s = (rec.get("deadline_s") if rec is not None
                              else (freq.deadline - time.perf_counter()
                                    if freq.deadline is not None
                                    else None))
                if self._failover_locked(freq, deadline_s, path):
                    moved += 1
                    slot.respawn_frids.append(freq.frid)
        return moved

    def _failover_locked(self, freq: _ProcRequest,
                         deadline_s: Optional[float],
                         path: str,
                         avoid_current: bool = True) -> bool:
        """Re-route one request to a surviving worker (caller holds the
        lock). The session's pin MOVES with it. Returns True when the
        request found a new home. In a disaggregated fleet the REDO
        pool is the PREFILL side regardless of where the request died:
        a lost decode worker took the spliced KV with it, so the only
        way forward is a fresh prefill -> handoff chain (greedy chains
        are deterministic per request — the re-run is byte-identical).

        ``avoid_current=False`` keeps the request's CURRENT worker in
        the candidate pool: a handoff-ship failure redoes from a
        healthy prefill worker — excluding it (the rule for a dying
        worker) would dead-end a 1-prefill fleet for no reason."""
        freq.failovers += 1
        if freq.failovers > self.max_failovers:
            self._finish_locked(freq, None, "engine_fault")
            return False
        tried = {freq.worker} if avoid_current else set()
        while True:
            pool = [s for s in self.slots
                    if s.routable and s.idx not in tried
                    and (self.roles is None or s.role == "prefill")]
            if not pool:
                self._finish_locked(freq, None, "engine_fault")
                return False
            slot = min(pool, key=lambda s: (s.inflight, s.idx))
            try:
                rid = self._rpc(
                    slot, "submit_ids",
                    {"input_ids": freq.input_ids,
                     "pixel_values": freq.pixel_values,
                     "max_new_tokens": freq.max_new_tokens,
                     "deadline_s": deadline_s, "slo": freq.slo},
                    retry_sent=False)
                break
            except (rpc.RpcError, rpc.RpcRemoteError) as e:
                with_fault = repr(e)
                tried.add(slot.idx)
                if isinstance(e, rpc.RpcError):
                    slot.state = "suspect"
                    self._export_routable_gauge()
                self.fault = with_fault
        old = freq.worker
        self.slots[old].inflight = max(self.slots[old].inflight - 1, 0)
        freq.worker = slot.idx
        freq.rid = rid
        freq.t_assign = time.perf_counter()
        # The abandoned attempt's prefill/handoff legs must not sum
        # into the final timeline — their wall time is exactly what
        # failover_redo_s charges (t_submit -> this assignment).
        freq.prefill_phases = None
        freq.handoff_s = 0.0
        slot.inflight += 1
        self._pins[freq.key] = slot.idx
        self.n_failovers += 1
        obs_metrics.PROCFLEET_FAILOVERS.inc(
            path=("drain" if path == "drain" else "redo"))
        obs_metrics.FLEET_ROUTED.inc(reason="repin")
        obs_journey.event(self._journey_owner, freq.frid, "failover",
                          from_worker=old, to_worker=slot.idx,
                          worker_rid=rid, path=path)
        obs_journey.event(self._journey_owner, freq.frid, "repin",
                          worker=slot.idx)
        return True

    def _stitch_locked(self, freq: _ProcRequest,
                       worker_journey: Optional[dict]):
        """(t_submit, t_done, phases) stitched across processes from
        DURATIONS (worker clocks are not comparable): the final
        assignment's worker-measured phases + ``failover_redo_s`` =
        coordinator wall time from first submit to the final
        assignment. A disaggregated request stitches THREE legs: the
        prefill worker's closed phase decomposition (rides the handoff
        record) sums keywise into the decode leg's, ``handoff_s`` is
        the coordinator-measured collect->import wall time, and
        ``failover_redo_s`` covers any abandoned chains before the
        final one. The phase-sum invariant holds by construction.
        When the worker timeline is unavailable (its recorder
        disarmed, or the worker is gone) a failed-over request still
        charges redo honestly — the final leg's unattributed time
        lands in decode_s, the phase it overwhelmingly is."""
        redo = (max(freq.t_assign - freq.t_submit, 0.0)
                if freq.failovers else 0.0)
        if worker_journey is None or not worker_journey.get("phases"):
            if not freq.failovers and not freq.handoff_s:
                return None
            t_done = time.perf_counter()
            phases = {k: 0.0 for k in obs_journey.PHASE_KEYS}
            phases["handoff_s"] = freq.handoff_s
            phases["decode_s"] = max(
                t_done - freq.t_submit - redo - freq.handoff_s, 0.0)
            phases["failover_redo_s"] = redo
            return freq.t_submit, t_done, phases
        phases = dict(worker_journey["phases"])
        leg_e2e = sum(v for k, v in worker_journey["phases"].items()
                      if k not in ("failover_redo_s", "handoff_s"))
        prefill_e2e = 0.0
        if freq.prefill_phases:
            for k, v in freq.prefill_phases.items():
                if k in ("failover_redo_s", "handoff_s"):
                    continue
                phases[k] = phases.get(k, 0.0) + v
                prefill_e2e += v
        phases["handoff_s"] = freq.handoff_s
        phases["failover_redo_s"] = redo
        t_done = (freq.t_submit + redo + prefill_e2e
                  + freq.handoff_s + leg_e2e)
        return freq.t_submit, t_done, phases

    def _finish_locked(self, freq: _ProcRequest, tokens, status: str,
                       worker_journey: Optional[dict] = None) -> None:
        freq.tokens = tokens
        freq.status = status
        if obs_journey.enabled():
            stitched = self._stitch_locked(freq, worker_journey)
            slo_met = freq.stats.get("slo_met")
            obs_journey.finish(
                self._journey_owner, freq.frid, status,
                t_submit=(stitched[0] if stitched else freq.t_submit),
                t_done=(stitched[1] if stitched else None),
                slo_class=getattr(freq.slo, "name", None),
                slo_met=(bool(slo_met) if slo_met is not None else None),
                phases=(stitched[2] if stitched else None),
                failovers=freq.failovers)
        if freq.stream and freq.stream_q is not None:
            # Deliver-at-finish streaming (see the module docstring):
            # one cumulative delta, then the engine stream protocol's
            # terminal sentinel.
            if tokens is not None:
                freq.stream_q.put(list(tokens))
                freq.stream_q.put(None if status == "ok"
                                  else {"status": status})
            else:
                freq.stream_q.put({"fault": str(self.fault)})
        if 0 <= freq.worker < len(self.slots):
            s = self.slots[freq.worker]
            s.inflight = max(s.inflight - 1, 0)
        freq.done.set()
        while len(self._requests) >= 8192:
            oldest = next(iter(self._requests))
            if not self._requests[oldest].done.is_set():
                break  # never evict a live request
            self._requests.pop(oldest)

    def _supervise(self) -> None:
        """The supervisor loop (never dies): readiness, liveness (poll
        + heartbeat + RPC probe), scripted chaos kills, respawn with
        backoff, and result collection."""
        while not self._stop:
            try:
                for slot in self.slots:
                    self._probe(slot)
                try:
                    faults.maybe_fail("procfleet.worker_kill")
                except faults.InjectedFault:
                    # The chaos trip IS the SIGKILL: take down the
                    # busiest routable worker — the worst case, it
                    # holds in-flight decodes that must be redone.
                    pool = [s for s in self.slots if s.routable]
                    if pool:
                        victim = max(pool,
                                     key=lambda s: (s.inflight, -s.idx))
                        self.kill_worker(victim.idx)
                self._pump_handoffs()
                self._collect()
                self._export_routable_gauge()
            except Exception as e:  # defensive: supervision must survive
                with self._lock:
                    self.fault = repr(e)
            time.sleep(self.probe_interval_s)

    def _probe(self, slot: WorkerSlot) -> None:
        if slot.state == "failed":
            return
        if slot.state == "starting":
            self._check_ready(slot)
            return
        if slot.state == "dead":
            self._maybe_respawn(slot)
            return
        # ok / suspect / draining: the process must still exist.
        if slot.proc is not None and slot.proc.poll() is not None:
            rc = slot.proc.returncode
            slot.proc = None
            slot.addr = None
            prev = slot.state
            self._book_crash(
                slot, f"worker {slot.idx} exited rc={rc} "
                      f"(state was {prev})")
            self._on_worker_lost(
                slot, f"worker {slot.idx} died (rc={rc})",
                graceful=False)
            return
        if slot.state == "draining":
            return  # drain_worker owns this slot's transitions
        # Heartbeat staleness: a wedged worker (process alive, loop
        # stuck) is drained while its RPC server still answers.
        if slot.hb_dir is not None:
            from eventgpt_tpu.train.resilience import Heartbeat

            hb_path = os.path.join(slot.hb_dir, Heartbeat.FILENAME)
            if os.path.exists(hb_path) and Heartbeat.is_stale(
                    hb_path, self.heartbeat_stale_s):
                self.drain_worker(slot.idx)
                return
        # RPC probe: lock-free ops only (snapshot) — a worker busy
        # compiling holds the engine lock, and probing through it
        # would misread SLOW as DEAD.
        try:
            snap = self._rpc(slot, "snapshot", deadline_s=5.0)
            slot.snapshot = snap or {}
            if slot.state == "suspect":
                slot.state = "ok"
                self._export_routable_gauge()
        except rpc.RpcError:
            if slot.state == "suspect":
                # Second strike: it answered neither the submit nor
                # the probe — drain it (the drain's own RPC failure
                # escalates to the hard-loss redo path).
                self.drain_worker(slot.idx)
            else:
                slot.state = "suspect"
                self._export_routable_gauge()

    def _collect(self) -> None:
        """Harvest finished requests: one batched ``try_results`` RPC
        per worker holding live assignments; engine-faulted requests
        fail over (redo)."""
        with self._lock:
            live = [f for f in self._requests.values()
                    if not f.done.is_set()]
        by_slot: Dict[int, List[_ProcRequest]] = {}
        for freq in live:
            by_slot.setdefault(freq.worker, []).append(freq)
        for idx, freqs in by_slot.items():
            slot = self.slots[idx]
            if slot.addr is None:
                continue
            try:
                got = self._rpc(slot, "try_results",
                                {"rids": [f.rid for f in freqs]},
                                deadline_s=self.rpc_deadline_s)
            except rpc.RpcError:
                continue  # probe handles slot health
            for freq in freqs:
                rec = (got or {}).get(str(freq.rid))
                if rec is None:
                    continue
                with self._lock:
                    if freq.done.is_set() or freq.worker != idx:
                        continue  # failed over meanwhile
                    if rec["status"] == "engine_fault":
                        remaining = (
                            freq.deadline - time.perf_counter()
                            if freq.deadline is not None else None)
                        self._failover_locked(freq, remaining, "redo")
                        continue
                    freq.stats = dict(rec.get("stats") or {})
                    self._finish_locked(freq, rec["tokens"],
                                        rec["status"],
                                        worker_journey=rec.get("journey"))

    # -- prefill/decode handoff pump (ISSUE 17) ----------------------------

    def _pump_handoffs(self) -> None:
        """Move gathered block runs from prefill outboxes to decode
        arenas (supervisor tick). Delivery is at-least-once end to end:
        unacked records replay from the prefill worker, the decode
        worker's hid dedup absorbs the duplicates."""
        if self.roles is None:
            return
        for slot in self.slots:
            if slot.role != "prefill" or slot.addr is None:
                continue
            if slot.state not in ("ok", "draining"):
                continue
            self._pump_slot_handoffs(slot)

    def _pump_slot_handoffs(self, slot: WorkerSlot) -> None:
        """One collect -> ship* -> ack pass over ``slot``'s outbox
        (serialized by ``_pump_lock``: the supervisor's periodic pump
        and a drain's flush must not ship the same replayed record
        concurrently)."""
        with self._pump_lock:
            try:
                recs = self._rpc(slot, "collect_handoffs",
                                 deadline_s=self.rpc_deadline_s)
            except rpc.RpcError:
                return  # probe handles slot health; records replay
            acked: List[int] = []
            for out in recs or []:
                try:
                    if self._ship_handoff(slot, out):
                        acked.append(int(out["rid"]))
                except Exception as e:  # defensive: one bad record
                    acked.append(int(out["rid"]))  # must not wedge
                    with self._lock:              # the whole outbox
                        self.fault = f"handoff ship failed: {e!r}"
            if acked:
                try:
                    self._rpc(slot, "ack_handoffs", {"rids": acked},
                              deadline_s=10.0)
                except rpc.RpcError:
                    pass  # re-served next collect; hid dedup absorbs

    def _ship_handoff(self, src: WorkerSlot, out: dict) -> bool:
        """Ship one gathered record to a decode worker. True = the
        record is settled at the source (shipped, stale, or fallen
        back to REDO) and can be acked; False keeps it replayable
        (transient: no decode worker reachable right now). Each
        attempt probes the ``procfleet.handoff`` fault site — a trip
        is a transport failure mid-ship that the bounded retry loop
        must absorb without ever double-splicing."""
        src_rid = int(out["rid"])
        with self._lock:
            freq = next(
                (f for f in self._requests.values()
                 if f.worker == src.idx and f.rid == src_rid
                 and not f.done.is_set()), None)
        if freq is None:
            return True  # stale replay: the request moved on already
        # The spawn generation is part of the identity: a respawned
        # prefill worker's engine rid counter restarts at 0, so a bare
        # slot:rid pair would collide with a pre-respawn record still
        # sitting in a decode worker's dedup cache — the import would
        # "dedup" onto a long-finished stranger's rid.
        hid = f"{src.idx}.{src.generation}:{src_rid}"
        rec = out.get("rec") or {}
        nbytes = int(rec.get("nbytes_kv", 0))
        n_blocks = int(rec.get("n_blocks", 0))
        t0 = time.perf_counter()
        tried: set = set()
        attempts = 0
        rid2 = None
        dslot = None
        while attempts < max(self.handoff_retries, 1):
            with self._lock:
                dslot = self._route_decode_locked(exclude=tried)
            if dslot is None:
                break
            attempts += 1
            try:
                faults.maybe_fail("procfleet.handoff")
                faults.maybe_delay("procfleet.handoff")
                rid2 = self._rpc(
                    dslot, "import_handoff",
                    {"hid": hid,
                     "input_ids": out["input_ids"],
                     "tokens": out.get("tokens") or [],
                     "max_new_tokens": out["max_new_tokens"],
                     "prompt_len": out.get("prompt_len", 0),
                     "deadline_s": out.get("deadline_s"),
                     "slo": out.get("slo"),
                     "elapsed_s": out.get("elapsed_s"),
                     "ttft_s": out.get("ttft_s"),
                     "rec": rec},
                    retry_sent=False)
                break
            except (faults.InjectedFault, rpc.RpcError,
                    rpc.RpcRemoteError) as e:
                rid2 = None
                tried.add(dslot.idx)
                with self._lock:
                    self.n_handoff_retries += 1
                    self.fault = (f"handoff {hid} -> worker "
                                  f"{dslot.idx}: {e!r}")
                if isinstance(e, rpc.RpcError):
                    dslot.state = "suspect"
                    self._export_routable_gauge()
        if rid2 is None:
            if attempts == 0:
                return False  # no decode worker up: keep it replayable
            # Retries exhausted: the REDO fallback — re-prefill from
            # the coordinator's own record. Never a double splice: no
            # import succeeded, so the shipped KV reached no arena.
            with self._lock:
                if freq.done.is_set() or freq.worker != src.idx:
                    return True
                self.n_handoff_redos += 1
                deadline_s = (freq.deadline - time.perf_counter()
                              if freq.deadline is not None else None)
                # The source prefill worker is HEALTHY (the failure was
                # on the decode side): keep it in the redo pool.
                self._failover_locked(freq, deadline_s, "redo",
                                      avoid_current=False)
            return True
        dt = time.perf_counter() - t0
        with self._lock:
            already = (freq.worker == dslot.idx and freq.rid == rid2)
            moved = freq.done.is_set() or freq.worker != src.idx
            if not moved:
                src.inflight = max(src.inflight - 1, 0)
                freq.worker = dslot.idx
                freq.rid = int(rid2)
                freq.prefill_phases = ((out.get("journey") or {})
                                       .get("phases") or None)
                freq.handoff_s += dt
                dslot.inflight += 1
                self.n_handoffs += 1
                self.n_handoff_bytes += nbytes
        if moved:
            if not already:
                # The request finished/failed over while we shipped:
                # the import is an orphan — cancel it best-effort (a
                # missed cancel decodes into the replay cache and ages
                # out; it can never double-deliver).
                try:
                    self._rpc(dslot, "cancel", {"rid": int(rid2)},
                              deadline_s=5.0)
                except rpc.RpcError:
                    pass
            return True
        obs_metrics.PROCFLEET_HANDOFFS.inc(stage="shipped")
        obs_metrics.PROCFLEET_HANDOFF_BYTES.inc(nbytes)
        obs_metrics.PROCFLEET_HANDOFF_SECONDS.observe(dt)
        obs_journey.event(self._journey_owner, freq.frid, "kv_handoff",
                          stage="shipped", from_worker=src.idx,
                          to_worker=dslot.idx, worker_rid=int(rid2),
                          bytes=nbytes, blocks=n_blocks)
        return True

    def _export_routable_gauge(self) -> None:
        obs_metrics.PROCFLEET_ROUTABLE.set(
            sum(s.routable for s in self.slots))

    # -- shutdown ----------------------------------------------------------

    def shutdown(self) -> None:
        """Coordinator shutdown drains before it exits (robustness
        layer 4): wait (bounded) for in-flight requests, ask every
        worker to stop over RPC, then escalate terminate -> kill."""
        if self._stop:
            return
        deadline = time.monotonic() + self.shutdown_drain_s
        while time.monotonic() < deadline:
            with self._lock:
                live = any(not f.done.is_set()
                           for f in self._requests.values())
            if not live:
                break
            time.sleep(0.05)
        self._stop = True
        if getattr(self, "_thread", None) is not None:
            self._thread.join(timeout=10)
        for slot in self.slots:
            if slot.addr is not None:
                try:
                    self._rpc(slot, "shutdown", deadline_s=5.0)
                except rpc.RpcError:
                    pass
        for slot in self.slots:
            if slot.proc is None:
                continue
            try:
                slot.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                try:
                    slot.proc.terminate()
                    slot.proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    self._kill_proc(slot)
            slot.proc = None
        if self._tmpdir is not None:
            try:
                self._tmpdir.cleanup()
            except OSError:
                pass


def stub_worker_cmd(token_delay_s: float = 0.005) -> List[str]:
    """The jax-free stub worker command (coordinator-logic tests)."""
    return [sys.executable, "-m", "eventgpt_tpu.fleet_proc",
            "--stub_worker", "--token_delay_s", str(token_delay_s)]


if __name__ == "__main__":
    sys.exit(_stub_main())
