"""HBM KV block-pool allocator (ISSUE 12 tentpole, host side).

The dense serving cache makes concurrency a function of ``batch ×
max_len``: every admitted row owns a full ``max_len`` run of KV slots
whether it uses them or not, and PR 9's capacity model shows that dead
padding IS the measured batch ceiling (14.78 GiB static at batch 40 →
runtime OOM). The paged layout (PagedAttention, vLLM SOSP '23) splits
the resident cache into one static arena of ``n_blocks`` fixed-size
blocks (``block_size == SEQ_BUCKET`` — the serving grain, so prompt
buckets and prefix-entry buckets are always whole-block runs) plus a
per-row int32 block table. Every jit-visible shape stays static; what
becomes dynamic is purely HOST bookkeeping — which pool block backs
which logical row position — and that bookkeeping lives here.

This class is the ONE allocator the refactor unifies row allocation,
prefix-entry pinning and copy-on-write around:

  * ``alloc(n)`` hands out ``n`` blocks at refcount 1 (or None — the
    admission gate: a request only admits when its whole reservation
    fits, so decode can never OOM mid-flight);
  * ``incref``/``decref`` implement prefix sharing: a prefix-cache hit
    aliases the entry's full blocks into the new row's table instead of
    copying them, and the block returns to the free list only when its
    LAST owner (rows + the cache entry itself) drops it;
  * ``cow`` is the copy-on-write primitive: a writer that holds a
    shared block trades it for a private copy target (the device copy
    is the caller's admission scatter — see ``serve.py``), bumping
    ``cow_copies`` so sharing efficiency is observable;
  * block 0 is the permanently-reserved SCRATCH block: free rows' and
    finished rows' tables point at it, so the segment kernels'
    unconditional frozen-row writes (the donated-aliasing rule) land in
    storage nothing ever reads — never in a recycled block another
    request now owns.

Thread contract: the owning ``ContinuousBatcher`` is externally
serialized, but HTTP handler threads read ``stats()`` — so every
mutation and compound read runs under ``_lock`` (the ``_GUARDED_BY``
annotations below are enforced by egpt-check rule ``lock``, and the
spy-lock test in ``tests/test_paged_blocks.py`` holds alloc/free inside
the critical section). Lock order: ``PrefixCache._lock ->
BlockPool._lock`` (entry eviction releases blocks while holding the
trie lock); this lock is a leaf above only the metric locks.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

from eventgpt_tpu.obs import memory as obs_memory
from eventgpt_tpu.obs import metrics as obs_metrics

# Reserved scratch block: free/finished rows' block tables point here so
# frozen-row garbage writes can never land in a recycled block.
SCRATCH_BLOCK = 0


class BlockPoolError(RuntimeError):
    """Allocator invariant violated (double free, unknown block, refcount
    underflow) — a bug, never an overload signal (overload is ``alloc``
    returning None)."""


class BlockPool:
    """Refcounted free-list allocator over ``n_blocks`` pool blocks of
    ``block_size`` KV positions each.

    ``n_blocks`` counts the whole arena INCLUDING the scratch block, so
    ``usable`` (= n_blocks - 1) is the real capacity the admission gate
    sees. ``block_bytes`` is carried for observability only (the gauges
    and ``stats()`` report bytes alongside block counts).
    """

    # Lock-discipline contract (egpt-check rule ``lock``): the free
    # list, refcounts, spill registry and counters only move under the
    # pool lock.
    _GUARDED_BY = {
        "_free": "_lock",
        "_refs": "_lock",
        "_spilled": "_lock",
        "_next_spill_id": "_lock",
        "allocs": "_lock",
        "frees": "_lock",
        "cow_copies": "_lock",
        "alloc_failures": "_lock",
        "spills": "_lock",
        "restores": "_lock",
    }

    def __init__(self, n_blocks: int, block_size: int,
                 block_bytes: int = 0):
        if n_blocks < 2:
            raise ValueError(
                f"block pool needs >= 2 blocks (1 scratch + 1 usable), "
                f"got {n_blocks}")
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self.block_bytes = int(block_bytes)
        self._lock = threading.Lock()
        # Refcount per block; scratch is permanently pinned at 1 so it
        # can never be handed out or freed.
        self._refs: List[int] = [0] * self.n_blocks
        self._refs[SCRATCH_BLOCK] = 1
        # LIFO free list: recently-freed blocks are re-used first, which
        # keeps the touched working set small.
        self._free: List[int] = list(range(self.n_blocks - 1, 0, -1))
        self.allocs = 0
        self.frees = 0
        self.cow_copies = 0
        self.alloc_failures = 0
        # Spill registry (ISSUE 16): run_id -> block count of a row's
        # KV run whose BYTES left the arena for the host-RAM SpillStore.
        # The device blocks themselves return to the free list at spill
        # time; the registry only remembers how many blocks the run
        # needs back so ``restore`` stays a plain allocation with a
        # loud-failure identity check.
        self._spilled: Dict[int, int] = {}
        self._next_spill_id = 0
        self.spills = 0
        self.restores = 0
        self._export_gauges_locked()

    # -- capacity ---------------------------------------------------------

    @property
    def usable(self) -> int:
        """Blocks the allocator can ever hand out (excludes scratch)."""
        return self.n_blocks - 1

    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def used_blocks(self) -> int:
        with self._lock:
            return self.usable - len(self._free)

    def free_bytes(self) -> int:
        """Free capacity in bytes (``free_blocks * block_bytes``; 0 when
        the pool was built without a byte size) — the decode-placement
        headroom signal the disaggregated router balances on (ISSUE 17):
        block counts only compare within one worker's geometry, bytes
        compare across a fleet."""
        with self._lock:
            return len(self._free) * self.block_bytes

    def blocks_for(self, positions: int) -> int:
        """Blocks covering ``positions`` KV slots (ceil at the block
        grain) — the reservation arithmetic shared by admission gating,
        the mem-guard repricing and the ledger's closed form."""
        return (max(int(positions), 0) + self.block_size - 1) \
            // self.block_size

    # -- alloc / free -----------------------------------------------------

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh blocks at refcount 1, or None when the pool cannot
        cover them (the caller defers admission — never a partial
        grant, so a failed admission holds nothing to unwind)."""
        if n <= 0:
            return []
        with self._lock:
            if n > len(self._free):
                self.alloc_failures += 1
                obs_metrics.SERVE_KV_ALLOC_FAILURES.inc()
                return None
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._refs[b] = 1
            self.allocs += n
            self._export_gauges_locked()
        return out

    def incref(self, blocks: Sequence[int]) -> None:
        """Add one owner to each block (prefix-entry aliasing)."""
        with self._lock:
            for b in blocks:
                self._check_live_locked(b)
                self._refs[b] += 1

    def decref(self, blocks: Sequence[int]) -> int:
        """Drop one owner from each block; blocks reaching refcount 0
        return to the free list. Returns how many were actually freed."""
        freed = 0
        with self._lock:
            for b in blocks:
                self._check_live_locked(b)
                self._refs[b] -= 1
                if self._refs[b] == 0:
                    self._free.append(b)
                    freed += 1
            self.frees += freed
            self._export_gauges_locked()
        return freed

    def cow(self, block: int) -> Optional[int]:
        """Copy-on-write: trade one reference on a SHARED ``block`` for a
        private block. Returns the private target (the caller performs
        the device copy / re-scatter), or ``block`` itself when it is
        already exclusively owned (no copy needed), or None when the
        pool has no room for the copy. Counts a copy only when one
        actually happens — ``egpt_serve_kv_cow_copies_total``."""
        with self._lock:
            self._check_live_locked(block)
            if self._refs[block] == 1:
                return block
            if not self._free:
                self.alloc_failures += 1
                obs_metrics.SERVE_KV_ALLOC_FAILURES.inc()
                return None
            new = self._free.pop()
            self._refs[new] = 1
            self._refs[block] -= 1
            self.allocs += 1
            self.cow_copies += 1
            obs_metrics.SERVE_KV_COW_COPIES.inc()
            self._export_gauges_locked()
            return new

    def note_cow(self) -> None:
        """Count a copy-on-write copy performed OUTSIDE ``cow`` — the
        serving admission path re-creates a divergent boundary block via
        its scatter (the copy and the write are one dispatch) rather
        than calling ``cow`` per block."""
        with self._lock:
            self.cow_copies += 1
        obs_metrics.SERVE_KV_COW_COPIES.inc()

    # -- spill / restore (ISSUE 16) ---------------------------------------

    def spill_out(self, blocks: Sequence[int]) -> int:
        """Evict an EXCLUSIVELY-OWNED block run from the arena: every
        block must be live at refcount exactly 1 (a pinned / aliased
        block has another owner whose table would dangle — refused with
        ``BlockPoolError``, and the caller falls back to
        drop-and-re-prefill). The blocks return to the free list — the
        caller has already gathered their bytes to the host — and the
        returned ``run_id`` names the registry entry ``restore`` checks
        against. Spilling a block twice fails naturally: the first
        spill freed it, so ``_check_live_locked`` raises."""
        blocks = list(blocks)
        with self._lock:
            for b in blocks:
                self._check_live_locked(b)
                if self._refs[b] != 1:
                    raise BlockPoolError(
                        f"block {b} has refcount {self._refs[b]}: "
                        f"spill-while-pinned refused (an aliased owner "
                        f"would dangle)")
            for b in blocks:
                self._refs[b] = 0
                self._free.append(b)
            run_id = self._next_spill_id
            self._next_spill_id += 1
            self._spilled[run_id] = len(blocks)
            self.frees += len(blocks)
            self.spills += 1
            self._export_gauges_locked()
        return run_id

    def restore(self, run_id: int, n: int) -> Optional[List[int]]:
        """Re-admit a spilled run: ``n`` fresh blocks (the caller
        scatters the host bytes back through the paged admission seam),
        or None when the pool cannot cover them yet — the run stays
        registered and restorable. An unknown / already-restored /
        dropped ``run_id`` is a lifecycle bug and raises loudly."""
        with self._lock:
            if run_id not in self._spilled:
                raise BlockPoolError(
                    f"spill run {run_id} is not registered "
                    f"(already restored, dropped, or never spilled)")
            n = max(int(n), 0)
            if n > len(self._free):
                self.alloc_failures += 1
                obs_metrics.SERVE_KV_ALLOC_FAILURES.inc()
                return None
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._refs[b] = 1
            del self._spilled[run_id]
            self.allocs += n
            self.restores += 1
            self._export_gauges_locked()
        return out

    def drop_spilled(self, run_id: int) -> None:
        """Forget a spilled run without restoring it (the victim chose
        / fell back to re-prefill, or expired). Dropping an unknown run
        raises — a double drop means two owners thought they held it."""
        with self._lock:
            if run_id not in self._spilled:
                raise BlockPoolError(
                    f"spill run {run_id} is not registered "
                    f"(double drop, or already restored)")
            del self._spilled[run_id]

    def spilled_runs(self) -> int:
        with self._lock:
            return len(self._spilled)

    def ref(self, block: int) -> int:
        with self._lock:
            return self._refs[block]

    def _check_live_locked(self, b: int) -> None:
        if b == SCRATCH_BLOCK:
            raise BlockPoolError("scratch block is not refcounted")
        if not (0 < b < self.n_blocks):
            raise BlockPoolError(f"block {b} out of range")
        if self._refs[b] <= 0:
            raise BlockPoolError(f"block {b} is free (double free?)")

    # -- observability ----------------------------------------------------

    def _export_gauges_locked(self) -> None:
        obs_metrics.SERVE_KV_BLOCKS_FREE.set(len(self._free))
        obs_metrics.SERVE_KV_BLOCKS_USED.set(self.usable - len(self._free))

    def stats(self) -> Dict[str, Any]:
        """Snapshot for ``GET /memory`` / bench records (lock-held)."""
        with self._lock:
            free = len(self._free)
            n_spilled = len(self._spilled)
            return {
                "n_blocks": self.n_blocks,
                "block_size": self.block_size,
                "block_bytes": self.block_bytes,
                "usable_blocks": self.usable,
                "free_blocks": free,
                "used_blocks": self.usable - free,
                "allocs": self.allocs,
                "frees": self.frees,
                "cow_copies": self.cow_copies,
                "alloc_failures": self.alloc_failures,
                "spills": self.spills,
                "restores": self.restores,
                "spilled_runs": n_spilled,
            }


class SpillStore:
    """Pinned host-RAM store for spilled KV runs (ISSUE 16).

    One record per preempted request: the gathered dense KV bytes plus
    whatever host state re-activation needs (length, logits row, spec
    ids). A byte BUDGET (``--spill_capacity_mb``) bounds resident host
    bytes — ``put`` refuses over-budget records (the caller falls back
    to drop-and-re-prefill) and the refusal count is the exhaustion
    signal the 503 admission path keys on. Resident bytes are priced
    into the memory ledger under the ``spill`` component so
    ``GET /memory`` and the bench records see the host tier next to the
    device tiers.

    Thread contract: the owning batcher is externally serialized but
    HTTP handler threads read ``stats()`` — mutations run under
    ``_lock``. Lock order: SpillStore._lock -> MemoryLedger lock ->
    metric locks (the ledger resize happens inside the critical
    section, matching the prefix cache's discipline).
    """

    _GUARDED_BY = {
        "_recs": "_lock",
        "used_bytes": "_lock",
        "puts": "_lock",
        "takes": "_lock",
        "drops": "_lock",
        "rejects": "_lock",
    }

    def __init__(self, capacity_bytes: int, owner: str = "spill"):
        self.capacity_bytes = max(int(capacity_bytes), 0)
        self._mem_key = f"{owner}/spill"
        self._lock = threading.Lock()
        self._recs: Dict[int, Dict[str, Any]] = {}
        self.used_bytes = 0
        self.puts = 0
        self.takes = 0
        self.drops = 0
        self.rejects = 0

    @property
    def enabled(self) -> bool:
        return self.capacity_bytes > 0

    def would_fit(self, nbytes: int) -> bool:
        with self._lock:
            return self.capacity_bytes - self.used_bytes >= int(nbytes)

    def put(self, rid: int, record: Dict[str, Any], nbytes: int) -> bool:
        """Admit one spilled run, or refuse (False) when the budget
        cannot cover it — never evicts: a spilled run is live request
        state, not a cache entry."""
        nbytes = int(nbytes)
        with self._lock:
            if rid in self._recs:
                raise BlockPoolError(
                    f"request {rid} already holds a spill record "
                    f"(double spill?)")
            if nbytes > self.capacity_bytes - self.used_bytes:
                self.rejects += 1
                return False
            record = dict(record)
            record["nbytes"] = nbytes
            self._recs[rid] = record
            self.used_bytes += nbytes
            self.puts += 1
            obs_memory.LEDGER.resize("spill", self._mem_key,
                                     self.used_bytes)
            obs_metrics.SERVE_SPILL_STORE_BYTES.set(self.used_bytes)
            obs_metrics.SERVE_SPILL_BYTES.inc(nbytes)
        return True

    def peek(self, rid: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._recs.get(rid)

    def take(self, rid: int) -> Dict[str, Any]:
        """Remove and return a record (restore succeeded / the caller
        owns the bytes now). Unknown rids raise — a restore of a run
        that was never spilled (or already taken) is a lifecycle bug."""
        with self._lock:
            rec = self._recs.pop(rid, None)
            if rec is None:
                raise BlockPoolError(
                    f"request {rid} holds no spill record "
                    f"(double restore, or never spilled)")
            self.used_bytes -= int(rec["nbytes"])
            self.takes += 1
            obs_memory.LEDGER.resize("spill", self._mem_key,
                                     self.used_bytes)
            obs_metrics.SERVE_SPILL_STORE_BYTES.set(self.used_bytes)
        return rec

    def drop(self, rid: int) -> None:
        """Discard a record without restoring (the victim expired or
        fell back to re-prefill). Unknown rids are a no-op — drop runs
        in terminal sweeps that may repeat."""
        with self._lock:
            rec = self._recs.pop(rid, None)
            if rec is None:
                return
            self.used_bytes -= int(rec["nbytes"])
            self.drops += 1
            obs_memory.LEDGER.resize("spill", self._mem_key,
                                     self.used_bytes)
            obs_metrics.SERVE_SPILL_STORE_BYTES.set(self.used_bytes)

    def clear(self) -> None:
        with self._lock:
            self._recs.clear()
            self.used_bytes = 0
            obs_memory.LEDGER.release("spill", self._mem_key)
            obs_metrics.SERVE_SPILL_STORE_BYTES.set(0)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "capacity_bytes": self.capacity_bytes,
                "used_bytes": self.used_bytes,
                "records": len(self._recs),
                "puts": self.puts,
                "takes": self.takes,
                "drops": self.drops,
                "rejects": self.rejects,
            }
