"""ctypes bridge to the native toolchain (native/libegpt_native.so).

pybind11 is not in this image, so the C ABI in ``native/src/capi.cpp`` is
bound with ctypes. The native rasterizer replaces the host hot spot
(``common/common.py:64-74`` measured at ~132k events/sample) with a single
linear C pass; the Python numpy scatter fallback stays available everywhere
the library has not been built.

Build:  cmake -S native -B native/build && cmake --build native/build -j
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_SEARCHED = False


def _candidate_paths():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for build in ("build", "build-release", "build-asan"):
        yield os.path.join(root, "native", build, "libegpt_native.so")
    env = os.environ.get("EGPT_NATIVE_LIB")
    if env:
        yield env


def load_library() -> Optional[ctypes.CDLL]:
    """Load libegpt_native.so if built; returns None (and remembers) if not."""
    global _LIB, _SEARCHED
    if _LIB is not None or _SEARCHED:
        return _LIB
    _SEARCHED = True
    for path in _candidate_paths():
        if not os.path.exists(path):
            continue
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            continue
        lib.egpt_rasterize.argtypes = [
            ctypes.POINTER(ctypes.c_uint16), ctypes.POINTER(ctypes.c_uint16),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.egpt_rasterize.restype = None
        _LIB = lib
        break
    return _LIB


def available() -> bool:
    return load_library() is not None


def rasterize_events_native(
    x: np.ndarray, y: np.ndarray, p: np.ndarray, height: int, width: int
) -> np.ndarray:
    """Native last-write-wins polarity raster; same semantics as
    ``ops.raster.rasterize_events``. Raises RuntimeError if the lib is absent."""
    lib = load_library()
    if lib is None:
        raise RuntimeError("libegpt_native.so not built; run scripts/build_native.sh")
    x = np.ascontiguousarray(x, dtype=np.uint16)
    y = np.ascontiguousarray(y, dtype=np.uint16)
    p = np.ascontiguousarray(p, dtype=np.uint8)
    out = np.empty(height * width * 3, dtype=np.uint8)
    lib.egpt_rasterize(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        p.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(x), height, width,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out.reshape(height, width, 3)
