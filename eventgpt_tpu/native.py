"""ctypes bridge to the native toolchain (native/libegpt_native.so).

pybind11 is not in this image, so the C ABI in ``native/src/capi.cpp`` is
bound with ctypes. The native rasterizer replaces the host hot spot
(``common/common.py:64-74`` measured at ~132k events/sample) with a single
linear C pass; the Python numpy scatter fallback stays available everywhere
the library has not been built.

Build:  cmake -S native -B native/build && cmake --build native/build -j
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_SEARCHED = False


def _candidate_paths():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for build in ("build", "build-release", "build-asan"):
        yield os.path.join(root, "native", build, "libegpt_native.so")
    env = os.environ.get("EGPT_NATIVE_LIB")
    if env:
        yield env


def load_library() -> Optional[ctypes.CDLL]:
    """Load libegpt_native.so if built; returns None (and remembers) if not."""
    global _LIB, _SEARCHED
    if _LIB is not None or _SEARCHED:
        return _LIB
    _SEARCHED = True
    for path in _candidate_paths():
        if not os.path.exists(path):
            continue
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            continue
        lib.egpt_rasterize.argtypes = [
            ctypes.POINTER(ctypes.c_uint16), ctypes.POINTER(ctypes.c_uint16),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.egpt_rasterize.restype = None
        _LIB = lib
        break
    return _LIB


def available() -> bool:
    return load_library() is not None


def rasterize_events_native(
    x: np.ndarray, y: np.ndarray, p: np.ndarray, height: int, width: int
) -> np.ndarray:
    """Native last-write-wins polarity raster; same semantics as
    ``ops.raster.rasterize_events``. Raises RuntimeError if the lib is absent."""
    lib = load_library()
    if lib is None:
        raise RuntimeError("libegpt_native.so not built; run scripts/build_native.sh")
    x = np.ascontiguousarray(x, dtype=np.uint16)
    y = np.ascontiguousarray(y, dtype=np.uint16)
    p = np.ascontiguousarray(p, dtype=np.uint8)
    out = np.empty(height * width * 3, dtype=np.uint8)
    lib.egpt_rasterize(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        p.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(x), height, width,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out.reshape(height, width, 3)


class EventStream:
    """Consumer handle over the native threaded event-stream producer
    (``native/include/egpt/events_io.hpp`` — the EventsDataIO PushData/
    PopDataUntil seam, EventsDataIO.cpp:53-145, across the C boundary).

    A producer thread replays a txt ("t x y p") or structured-npy file,
    optionally paced at wall-clock rate; ``pop_until(horizon)`` returns every
    event with t <= horizon as numpy arrays, splitting a straddling packet
    exactly like the reference consumer.

    ``time_unit``: txt timestamp unit — "auto" treats a max value > 1e5 as
    microseconds; microsecond recordings shorter than 0.1 s are ambiguous
    under auto and must pass "microseconds" explicitly.
    """

    def __init__(self, path: str, paced: bool = False, pace_factor: float = 1.0,
                 time_unit: str = "auto"):
        lib = load_library()
        if lib is None:
            raise RuntimeError(
                "libegpt_native.so not built; run scripts/build_native.sh"
            )
        self._lib = lib
        lib.egpt_stream_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_double,
            ctypes.c_int,
        ]
        lib.egpt_stream_open.restype = ctypes.c_void_p
        lib.egpt_stream_pop_until.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.egpt_stream_pop_until.restype = ctypes.c_int64
        lib.egpt_stream_fetch.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint16), ctypes.POINTER(ctypes.c_uint16),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.egpt_stream_fetch.restype = None
        lib.egpt_stream_running.argtypes = [ctypes.c_void_p]
        lib.egpt_stream_running.restype = ctypes.c_int
        lib.egpt_stream_close.argtypes = [ctypes.c_void_p]
        lib.egpt_stream_close.restype = None

        is_npy = 1 if path.endswith(".npy") else 0
        units = {"auto": 0, "seconds": 1, "microseconds": 2}
        if time_unit not in units:
            raise ValueError(f"time_unit must be one of {sorted(units)}")
        self._handle = lib.egpt_stream_open(
            path.encode(), is_npy, 1 if paced else 0, float(pace_factor),
            units[time_unit],
        )
        if not self._handle:
            raise FileNotFoundError(f"could not open event stream {path}")
        # GC safety net: a handle that is never close()d must not leak the
        # native producer thread/queue for the process lifetime. finalize is
        # idempotent with close() (detached there).
        import weakref

        self._finalizer = weakref.finalize(
            self, lib.egpt_stream_close, self._handle
        )

    def pop_until(self, horizon_s: float):
        """Events with t <= horizon (seconds) -> dict of numpy arrays
        {x: u16, y: u16, t: f64 seconds, p: u8}. Non-blocking."""
        n = self._lib.egpt_stream_pop_until(self._handle, float(horizon_s))
        if n < 0:
            raise RuntimeError("pop on a closed stream")
        x = np.empty(n, np.uint16)
        y = np.empty(n, np.uint16)
        t = np.empty(n, np.float64)
        p = np.empty(n, np.uint8)
        if n:
            self._lib.egpt_stream_fetch(
                self._handle,
                x.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                y.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                t.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                p.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            )
        return {"x": x, "y": y, "t": t, "p": p}

    def running(self) -> bool:
        """True while the producer thread is alive or events remain queued."""
        return bool(self._lib.egpt_stream_running(self._handle))

    def close(self) -> None:
        if self._handle:
            self._finalizer.detach()
            self._lib.egpt_stream_close(self._handle)
            self._handle = None

    def __enter__(self) -> "EventStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
