// C ABI for the Python side (ctypes — pybind11 is not in this image).
//
// Exposes the host hot ops so the JAX data pipeline can call into native
// code: rasterization (the measured hot spot, common/common.py:64-74),
// npy event loading, and the full load->split->rasterize pipeline.
#include <cstdint>
#include <cstring>
#include <vector>

#include "egpt/events_io.hpp"
#include "egpt/raster.hpp"

extern "C" {

// Rasterize n events into out (h*w*3 uint8, preallocated by caller).
void egpt_rasterize(const uint16_t* x, const uint16_t* y, const uint8_t* p,
                    size_t n, int height, int width, uint8_t* out) {
  egpt::RasterizeEvents(x, y, p, n, height, width, out);
}

// Load a structured npy; returns event count or -1. Caller then calls
// egpt_events_fetch to copy fields out and egpt_events_free to release.
struct EgptEvents {
  std::vector<egpt::Event> events;
};

void* egpt_events_load(const char* path) {
  auto* holder = new EgptEvents();
  if (!egpt::LoadEventsNpy(path, holder->events)) {
    delete holder;
    return nullptr;
  }
  return holder;
}

int64_t egpt_events_count(void* handle) {
  return handle ? static_cast<int64_t>(static_cast<EgptEvents*>(handle)->events.size()) : -1;
}

void egpt_events_fetch(void* handle, uint16_t* x, uint16_t* y, double* t, uint8_t* p) {
  auto* holder = static_cast<EgptEvents*>(handle);
  for (size_t i = 0; i < holder->events.size(); ++i) {
    x[i] = holder->events[i].x;
    y[i] = holder->events[i].y;
    t[i] = holder->events[i].t;
    p[i] = holder->events[i].p;
  }
}

void egpt_events_free(void* handle) { delete static_cast<EgptEvents*>(handle); }

// Full host pipeline: load npy -> n_frames equal-count slices -> rasterize.
// out must hold n_frames*height*width*3 bytes; height/width must be the
// stream's (max_y+1, max_x+1) or larger. Returns 0 on success.
int egpt_npy_to_frames(const char* path, int n_frames, int height, int width,
                       uint8_t* out) {
  std::vector<egpt::Event> events;
  if (!egpt::LoadEventsNpy(path, events)) return -1;
  if (events.size() < static_cast<size_t>(n_frames)) return -2;
  const auto slices = egpt::SplitByCount(events.size(), n_frames);
  const size_t frame_bytes = static_cast<size_t>(height) * width * 3;
  for (int i = 0; i < n_frames; ++i) {
    const auto [lo, hi] = slices[i];
    std::vector<uint16_t> xs(hi - lo), ys(hi - lo);
    std::vector<uint8_t> ps(hi - lo);
    for (size_t j = lo; j < hi; ++j) {
      xs[j - lo] = events[j].x;
      ys[j - lo] = events[j].y;
      ps[j - lo] = events[j].p;
    }
    egpt::RasterizeEvents(xs.data(), ys.data(), ps.data(), hi - lo, height,
                          width, out + static_cast<size_t>(i) * frame_bytes);
  }
  return 0;
}

// --- Streaming (EventsDataIO) ---------------------------------------------
// Two-phase pop: egpt_stream_pop_until stages events <= horizon into the
// handle and returns the count; egpt_stream_fetch copies them out. Mirrors
// the consumer side of the reference's PushData/PopDataUntil seam
// (EventsDataIO.cpp:53-145) across the C boundary.

struct EgptStream {
  egpt::EventsDataIO io;
  std::vector<egpt::Event> staged;
  EgptStream(const egpt::EventsDataIO::Options& o) : io(o) {}
};

// Open a file-backed stream. is_npy selects the structured-npy reader vs
// the "t x y p" txt reader; paced != 0 replays at wall-clock rate scaled
// by pace_factor; time_unit: 0 auto-detect, 1 seconds, 2 microseconds
// (txt only — short microsecond recordings are ambiguous under auto).
// Returns nullptr on open failure.
void* egpt_stream_open(const char* path, int is_npy, int paced,
                       double pace_factor, int time_unit) {
  egpt::EventsDataIO::Options opts;
  opts.paced = paced != 0;
  opts.pace_factor = pace_factor > 0 ? pace_factor : 1.0;
  opts.time_unit = static_cast<egpt::TimeUnit>(time_unit);
  auto* s = new EgptStream(opts);
  const bool ok = is_npy ? s->io.GoOfflineNpy(path) : s->io.GoOfflineTxt(path);
  if (!ok) {
    delete s;
    return nullptr;
  }
  return s;
}

int64_t egpt_stream_pop_until(void* handle, double horizon) {
  if (!handle) return -1;
  auto* s = static_cast<EgptStream*>(handle);
  s->staged.clear();
  s->io.PopDataUntil(horizon, s->staged);
  return static_cast<int64_t>(s->staged.size());
}

void egpt_stream_fetch(void* handle, uint16_t* x, uint16_t* y, double* t,
                       uint8_t* p) {
  auto* s = static_cast<EgptStream*>(handle);
  for (size_t i = 0; i < s->staged.size(); ++i) {
    x[i] = s->staged[i].x;
    y[i] = s->staged[i].y;
    t[i] = s->staged[i].t;
    p[i] = s->staged[i].p;
  }
}

int egpt_stream_running(void* handle) {
  return handle && static_cast<EgptStream*>(handle)->io.Running() ? 1 : 0;
}

void egpt_stream_close(void* handle) { delete static_cast<EgptStream*>(handle); }

}  // extern "C"
