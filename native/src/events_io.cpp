#include "egpt/events_io.hpp"

#include <algorithm>
#include <chrono>
#include <cstring>
#include <fstream>
#include <map>
#include <sstream>

namespace egpt {

// ---------------------------------------------------------------------------
// npy structured-array reader (schema of samples/sample1.npy: fields x,y,t,p)

namespace {

struct FieldDesc {
  char kind = 0;     // 'u', 'i', 'f'
  int size = 0;      // bytes
  size_t offset = 0;
};

double ReadField(const char* rec, const FieldDesc& f) {
  const char* p = rec + f.offset;
  switch (f.kind) {
    case 'u':
      switch (f.size) {
        case 1: { uint8_t v; std::memcpy(&v, p, 1); return v; }
        case 2: { uint16_t v; std::memcpy(&v, p, 2); return v; }
        case 4: { uint32_t v; std::memcpy(&v, p, 4); return v; }
        case 8: { uint64_t v; std::memcpy(&v, p, 8); return static_cast<double>(v); }
      }
      break;
    case 'i':
      switch (f.size) {
        case 1: { int8_t v; std::memcpy(&v, p, 1); return v; }
        case 2: { int16_t v; std::memcpy(&v, p, 2); return v; }
        case 4: { int32_t v; std::memcpy(&v, p, 4); return v; }
        case 8: { int64_t v; std::memcpy(&v, p, 8); return static_cast<double>(v); }
      }
      break;
    case 'f':
      switch (f.size) {
        case 4: { float v; std::memcpy(&v, p, 4); return v; }
        case 8: { double v; std::memcpy(&v, p, 8); return v; }
      }
      break;
  }
  return 0;
}

// Parse "('x', '<u2')" style tuples out of the header's descr list.
bool ParseDescr(const std::string& header, std::map<std::string, FieldDesc>& fields,
                size_t& itemsize) {
  const size_t dpos = header.find("'descr'");
  if (dpos == std::string::npos) return false;
  const size_t lb = header.find('[', dpos);
  const size_t rb = header.find(']', lb);
  if (lb == std::string::npos || rb == std::string::npos) return false;
  std::string body = header.substr(lb + 1, rb - lb - 1);

  size_t offset = 0;
  size_t pos = 0;
  while ((pos = body.find('(', pos)) != std::string::npos) {
    const size_t end = body.find(')', pos);
    if (end == std::string::npos) break;
    std::string tup = body.substr(pos + 1, end - pos - 1);
    // tokens: 'name', '<u2'
    std::vector<std::string> toks;
    size_t q = 0;
    while ((q = tup.find('\'', q)) != std::string::npos) {
      const size_t q2 = tup.find('\'', q + 1);
      if (q2 == std::string::npos) break;
      toks.push_back(tup.substr(q + 1, q2 - q - 1));
      q = q2 + 1;
    }
    if (toks.size() >= 2) {
      const std::string& name = toks[0];
      const std::string& dt = toks[1];
      FieldDesc f;
      size_t i = 0;
      if (dt[i] == '<' || dt[i] == '=' || dt[i] == '|' || dt[i] == '>') {
        if (dt[i] == '>') return false;  // big-endian unsupported
        ++i;
      }
      f.kind = dt[i];
      f.size = std::atoi(dt.c_str() + i + 1);
      f.offset = offset;
      offset += f.size;
      fields[name] = f;
    }
    pos = end + 1;
  }
  itemsize = offset;
  return !fields.empty();
}

}  // namespace

bool LoadEventsNpy(const std::string& path, std::vector<Event>& out) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return false;
  char magic[6];
  f.read(magic, 6);
  if (!f || std::memcmp(magic, "\x93NUMPY", 6) != 0) return false;
  uint8_t ver[2];
  f.read(reinterpret_cast<char*>(ver), 2);
  uint32_t header_len = 0;
  if (ver[0] == 1) {
    uint16_t hl;
    f.read(reinterpret_cast<char*>(&hl), 2);
    header_len = hl;
  } else {
    f.read(reinterpret_cast<char*>(&header_len), 4);
  }
  std::string header(header_len, '\0');
  f.read(header.data(), header_len);
  if (!f) return false;

  std::map<std::string, FieldDesc> fields;
  size_t itemsize = 0;
  if (!ParseDescr(header, fields, itemsize)) return false;
  if (!fields.count("x") || !fields.count("y") || !fields.count("t") || !fields.count("p"))
    return false;

  // shape: "(N,)"
  const size_t sp = header.find("'shape'");
  const size_t lp = header.find('(', sp);
  size_t n = std::strtoull(header.c_str() + lp + 1, nullptr, 10);

  std::vector<char> buf(itemsize * n);
  f.read(buf.data(), static_cast<std::streamsize>(buf.size()));
  if (!f) return false;

  const FieldDesc fx = fields["x"], fy = fields["y"], ft = fields["t"], fp = fields["p"];
  out.clear();
  out.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    const char* rec = buf.data() + i * itemsize;
    Event e;
    e.x = static_cast<uint16_t>(ReadField(rec, fx));
    e.y = static_cast<uint16_t>(ReadField(rec, fy));
    e.t = ReadField(rec, ft) * 1e-6;  // microseconds -> seconds
    e.p = static_cast<uint8_t>(ReadField(rec, fp));
    out.push_back(e);
  }
  return true;
}

bool SaveEventsNpy(const std::string& path, const std::vector<Event>& events) {
  // v1 .npy, structured dtype matching LoadEventsNpy's expectations and
  // the reference's sample files: t stored in MICROSECONDS (f8) so a
  // write->read round trip through either reader is exact.
  std::string descr =
      "{'descr': [('x', '<u2'), ('y', '<u2'), ('t', '<f8'), ('p', '<u1')], "
      "'fortran_order': False, 'shape': (" +
      std::to_string(events.size()) + ",), }";
  const size_t base = 6 + 2 + 2;  // magic + version + u16 header len
  size_t total = base + descr.size() + 1;  // +1 trailing newline
  const size_t pad = (64 - (total % 64)) % 64;
  descr.append(pad, ' ');
  descr.push_back('\n');

  std::ofstream f(path, std::ios::binary);
  if (!f) return false;
  f.write("\x93NUMPY", 6);
  const uint8_t ver[2] = {1, 0};
  f.write(reinterpret_cast<const char*>(ver), 2);
  const uint16_t hl = static_cast<uint16_t>(descr.size());
  f.write(reinterpret_cast<const char*>(&hl), 2);
  f.write(descr.data(), static_cast<std::streamsize>(descr.size()));
  for (const auto& e : events) {
    const double t_us = e.t * 1e6;
    f.write(reinterpret_cast<const char*>(&e.x), 2);
    f.write(reinterpret_cast<const char*>(&e.y), 2);
    f.write(reinterpret_cast<const char*>(&t_us), 8);
    f.write(reinterpret_cast<const char*>(&e.p), 1);
  }
  return static_cast<bool>(f);
}

bool LoadEventsTxt(const std::string& path, std::vector<Event>& out,
                   TimeUnit unit) {
  std::ifstream f(path);
  if (!f) return false;
  out.clear();
  std::string line;
  while (std::getline(f, line)) {
    if (line.empty() || line[0] == '#') continue;
    std::istringstream ss(line);
    double t, x, y, p;
    if (!(ss >> t >> x >> y >> p)) continue;
    Event e;
    e.t = t;
    e.x = static_cast<uint16_t>(x);
    e.y = static_cast<uint16_t>(y);
    e.p = static_cast<uint8_t>(p);
    out.push_back(e);
  }
  // Unit detection on the full stream's MAX (the file may be unsorted):
  // timestamps beyond 1e5 "seconds" (28 h) mean microseconds (the DSEC/npy
  // convention). Ambiguous for microsecond recordings shorter than 0.1 s —
  // pass an explicit Options::time_unit for those.
  if (unit == TimeUnit::kMicroseconds ||
      (unit == TimeUnit::kAuto && !out.empty() &&
       std::max_element(out.begin(), out.end(),
                        [](const Event& a, const Event& b) { return a.t < b.t; })
               ->t > 1e5)) {
    for (auto& e : out) e.t *= 1e-6;
  }
  return true;
}

// ---------------------------------------------------------------------------
// Threaded producer / consumer

void EventsDataIO::ProduceFromVector(std::vector<Event> events) {
  producing_ = true;
  const double packet_s = opts_.packet_us * 1e-6;
  const auto wall_start = std::chrono::steady_clock::now();
  const double t0 = events.empty() ? 0.0 : events.front().t;

  EventPacket packet;
  for (auto& e : events) {
    if (stop_requested_) break;
    if (packet.events.empty()) packet.t_begin = e.t;
    packet.events.push_back(e);
    packet.t_end = e.t;
    if (packet.t_end - packet.t_begin >= packet_s) {
      if (opts_.paced) {
        // Wall-clock pacing (EventsDataIO.cpp:329-335): sleep until the
        // packet's end time has elapsed in (scaled) real time.
        const double stream_elapsed = (packet.t_end - t0) / opts_.pace_factor;
        const auto target = wall_start + std::chrono::duration_cast<
            std::chrono::steady_clock::duration>(
                std::chrono::duration<double>(stream_elapsed));
        std::this_thread::sleep_until(target);
      }
      PushData(std::move(packet));
      packet = EventPacket{};
    }
  }
  if (!packet.events.empty() && !stop_requested_) PushData(std::move(packet));
  {
    // Flip under the mutex: a bare store + notify can fire between a
    // waiter's predicate check and its block (the predicate runs under
    // this mutex), losing the final wakeup — PopDataUntilBlocking would
    // then sleep forever at exactly the end-of-stream case.
    std::lock_guard<std::mutex> lock(mutex_);
    producing_ = false;
  }
  cv_.notify_all();
}

bool EventsDataIO::GoOfflineTxt(const std::string& path) {
  std::vector<Event> events;
  if (!LoadEventsTxt(path, events, opts_.time_unit)) return false;
  Stop();
  stop_requested_ = false;
  producing_ = true;
  producer_ = std::thread(&EventsDataIO::ProduceFromVector, this, std::move(events));
  return true;
}

bool EventsDataIO::GoOfflineNpy(const std::string& path) {
  std::vector<Event> events;
  if (!LoadEventsNpy(path, events)) return false;
  Stop();
  stop_requested_ = false;
  producing_ = true;
  producer_ = std::thread(&EventsDataIO::ProduceFromVector, this, std::move(events));
  return true;
}

void EventsDataIO::PushData(EventPacket&& packet) {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    queue_.push_back(std::move(packet));
  }
  cv_.notify_all();
}

size_t EventsDataIO::PopDataUntilBlocking(double horizon,
                                          std::vector<Event>& out) {
  {
    std::unique_lock<std::mutex> lock(mutex_);
    cv_.wait(lock, [&] {
      if (!producing_) return true;  // stream finished: drain what exists
      return !queue_.empty() && queue_.back().t_end > horizon;
    });
  }
  return PopDataUntil(horizon, out);
}

size_t EventsDataIO::PopDataUntil(double horizon, std::vector<Event>& out) {
  std::lock_guard<std::mutex> lock(mutex_);
  size_t popped = 0;
  while (!queue_.empty()) {
    EventPacket& front = queue_.front();
    if (front.t_begin > horizon) break;
    if (front.t_end <= horizon) {
      popped += front.events.size();
      out.insert(out.end(), front.events.begin(), front.events.end());
      queue_.pop_front();
      continue;
    }
    // Straddling packet: split at horizon, re-queue the tail
    // (EventsDataIO.cpp:80-145).
    auto it = std::partition_point(
        front.events.begin(), front.events.end(),
        [&](const Event& e) { return e.t <= horizon; });
    out.insert(out.end(), front.events.begin(), it);
    popped += static_cast<size_t>(it - front.events.begin());
    front.events.erase(front.events.begin(), it);
    front.t_begin = front.events.empty() ? horizon : front.events.front().t;
    break;
  }
  return popped;
}

bool EventsDataIO::Running() const {
  if (producing_) return true;
  std::lock_guard<std::mutex> lock(mutex_);
  return !queue_.empty();
}

void EventsDataIO::Stop() {
  stop_requested_ = true;
  cv_.notify_all();
  if (producer_.joinable()) producer_.join();
  std::lock_guard<std::mutex> lock(mutex_);
  queue_.clear();
}

size_t EventsDataIO::queue_size() const {
  std::lock_guard<std::mutex> lock(mutex_);
  return queue_.size();
}

}  // namespace egpt
