#include "egpt/config.hpp"

#include <fstream>
#include <sstream>

namespace egpt {

std::optional<Config> Config::Load(const std::string& path) {
  std::ifstream f(path);
  if (!f) return std::nullopt;
  std::stringstream ss;
  ss << f.rdbuf();
  return Parse(ss.str());
}

Config Config::Parse(const std::string& text) {
  Config cfg;
  std::istringstream ss(text);
  std::string line;
  while (std::getline(ss, line)) {
    const size_t hash = line.find('#');
    if (hash != std::string::npos) line = line.substr(0, hash);
    const size_t colon = line.find(':');
    if (colon == std::string::npos) continue;
    auto trim = [](std::string s) {
      const auto b = s.find_first_not_of(" \t\r\"[],");
      const auto e = s.find_last_not_of(" \t\r\"[],");
      return b == std::string::npos ? std::string() : s.substr(b, e - b + 1);
    };
    const std::string key = trim(line.substr(0, colon));
    std::string val = line.substr(colon + 1);
    // Strip list punctuation so "[1, 2, 3]" and "1 2 3" both parse.
    for (auto& c : val)
      if (c == '[' || c == ']' || c == ',') c = ' ';
    val = trim(val);
    if (!key.empty()) cfg.values_[key] = val;
  }
  return cfg;
}

std::optional<std::string> Config::get_str(const std::string& key) const {
  const auto it = values_.find(key);
  if (it == values_.end()) return std::nullopt;
  return it->second;
}

std::optional<double> Config::get_double(const std::string& key) const {
  const auto v = get_str(key);
  if (!v) return std::nullopt;
  try {
    return std::stod(*v);
  } catch (...) {
    return std::nullopt;
  }
}

std::optional<std::vector<double>> Config::get_doubles(const std::string& key) const {
  const auto v = get_str(key);
  if (!v) return std::nullopt;
  std::vector<double> out;
  std::istringstream ss(*v);
  double d;
  while (ss >> d) out.push_back(d);
  return out;
}

std::optional<RadtanCamera> Config::get_camera(const std::string& prefix) const {
  const auto intr = get_doubles(prefix + "_intrinsics");
  const auto res = get_doubles(prefix + "_resolution");
  if (!intr || intr->size() < 4 || !res || res->size() < 2) return std::nullopt;
  RadtanCamera cam;
  cam.K.fx = (*intr)[0];
  cam.K.fy = (*intr)[1];
  cam.K.cx = (*intr)[2];
  cam.K.cy = (*intr)[3];
  cam.K.width = static_cast<int>((*res)[0]);
  cam.K.height = static_cast<int>((*res)[1]);
  if (const auto dist = get_doubles(prefix + "_distortion");
      dist && dist->size() >= 4) {
    cam.D.k1 = (*dist)[0];
    cam.D.k2 = (*dist)[1];
    cam.D.p1 = (*dist)[2];
    cam.D.p2 = (*dist)[3];
    if (dist->size() >= 5) cam.D.k3 = (*dist)[4];
  }
  if (const auto ext = get_doubles(prefix + "_T_base_cam");
      ext && ext->size() >= 7) {
    cam.T_base_cam = SE3::from_quat_trans(
        (*ext)[0], (*ext)[1], (*ext)[2], (*ext)[3],
        {(*ext)[4], (*ext)[5], (*ext)[6]});
  }
  return cam;
}

}  // namespace egpt
