#include "egpt/rgbd.hpp"

#include <algorithm>
#include <cmath>
#include <fstream>

namespace egpt {

DepthMap ProjectDepth(const DepthMap& depth_src, const RadtanCamera& cam_src,
                      const RadtanCamera& cam_dst, double depth_scale,
                      int splat_radius) {
  std::vector<float> out(static_cast<size_t>(cam_dst.K.width) * cam_dst.K.height, 0.f);
  const SE3 T_dst_src = cam_dst.T_base_cam.inverse() * cam_src.T_base_cam;

  for (int y = 0; y < depth_src.height(); ++y) {
    for (int x = 0; x < depth_src.width(); ++x) {
      const float d = depth_src.at(x, y);
      if (d <= 0 || !std::isfinite(d)) continue;
      const double dm = d * depth_scale;
      const Vec3 p_src = cam_src.pixel_to_camera({static_cast<double>(x),
                                                  static_cast<double>(y)}, dm);
      const Vec3 p_dst = T_dst_src * p_src;
      const auto px = cam_dst.camera_to_pixel(p_dst);
      if (!px) continue;
      const int cx = static_cast<int>(std::lround(px->x));
      const int cy = static_cast<int>(std::lround(px->y));
      // Splat the pixel footprint with keep-min z-buffer
      // (RgbdDataIO.cpp:172-277 warps the footprint corners; a fixed splat
      // radius covers the same occlusion-filling purpose).
      for (int sy = cy - splat_radius; sy <= cy + splat_radius; ++sy) {
        if (sy < 0 || sy >= cam_dst.K.height) continue;
        for (int sx = cx - splat_radius; sx <= cx + splat_radius; ++sx) {
          if (sx < 0 || sx >= cam_dst.K.width) continue;
          float& slot = out[static_cast<size_t>(sy) * cam_dst.K.width + sx];
          const float dz = static_cast<float>(p_dst.z);
          if (slot <= 0 || dz < slot) slot = dz;
        }
      }
    }
  }
  return DepthMap(std::move(out), cam_dst.K.width, cam_dst.K.height);
}

namespace {

bool SkipWs(std::ifstream& f) {
  int c;
  while ((c = f.peek()) != EOF) {
    if (c == '#') {
      std::string line;
      std::getline(f, line);
    } else if (std::isspace(c)) {
      f.get();
    } else {
      break;
    }
  }
  return f.good();
}

}  // namespace

std::optional<DepthMap> ReadDepthPgm(const std::string& path, double scale_to_m) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return std::nullopt;
  std::string magic;
  f >> magic;
  if (magic != "P5") return std::nullopt;
  int w, h, maxval;
  SkipWs(f); f >> w;
  SkipWs(f); f >> h;
  SkipWs(f); f >> maxval;
  f.get();  // single whitespace after header
  std::vector<float> depth(static_cast<size_t>(w) * h);
  if (maxval > 255) {
    std::vector<uint8_t> raw(static_cast<size_t>(w) * h * 2);
    f.read(reinterpret_cast<char*>(raw.data()), static_cast<std::streamsize>(raw.size()));
    if (!f) return std::nullopt;
    for (size_t i = 0; i < depth.size(); ++i) {
      const uint16_t v = static_cast<uint16_t>((raw[2 * i] << 8) | raw[2 * i + 1]);
      depth[i] = static_cast<float>(v * scale_to_m);
    }
  } else {
    std::vector<uint8_t> raw(static_cast<size_t>(w) * h);
    f.read(reinterpret_cast<char*>(raw.data()), static_cast<std::streamsize>(raw.size()));
    if (!f) return std::nullopt;
    for (size_t i = 0; i < depth.size(); ++i)
      depth[i] = static_cast<float>(raw[i] * scale_to_m);
  }
  return DepthMap(std::move(depth), w, h);
}

bool ReadRgbPpm(const std::string& path, std::vector<uint8_t>& rgb, int& w, int& h) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return false;
  std::string magic;
  f >> magic;
  if (magic != "P6") return false;
  int maxval;
  SkipWs(f); f >> w;
  SkipWs(f); f >> h;
  SkipWs(f); f >> maxval;
  f.get();
  rgb.resize(static_cast<size_t>(w) * h * 3);
  f.read(reinterpret_cast<char*>(rgb.data()), static_cast<std::streamsize>(rgb.size()));
  return static_cast<bool>(f);
}

std::vector<float> RgbToGray(const std::vector<uint8_t>& rgb, int w, int h) {
  std::vector<float> gray(static_cast<size_t>(w) * h);
  for (size_t i = 0; i < gray.size(); ++i) {
    gray[i] = 0.299f * rgb[3 * i] + 0.587f * rgb[3 * i + 1] + 0.114f * rgb[3 * i + 2];
  }
  return gray;
}

}  // namespace egpt
