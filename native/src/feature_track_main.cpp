// Offline feature-track data generator — the end-to-end tool the reference's
// preprocess/feature_track/README.md:1-7 describes but never made buildable:
// detect features on RGB -> KLT-track -> RANSAC filter -> project RGB->event
// frame -> save (id, time window, prev/cur positions, events within an 11x11
// window around each feature).
//
// Usage:
//   egpt_feature_track <config.yaml> <out.csv> [npy_out_dir]
//
// Config keys (flat YAML, see egpt/config.hpp): rgb_* and event_* camera
// blocks, data_path with frame_%06d.ppm / depth_%06d.pgm pairs, events.npy,
// num_frames, frame_dt.
//
// With npy_out_dir, each tracked frame interval additionally writes its
// popped events as events_%06d.npy (the structured {x,y,t,p} layout the
// JAX pipeline's ops/raster.load_event_npy reads) — the SURVEY §2.3 seam:
// eventgpt_tpu/data/feature_track.py turns tracks.csv + these windows into
// auto-labeled motion-QA training samples for EventChatDataset.
#include <cstdio>
#include <fstream>
#include <iostream>
#include <vector>

#include "egpt/config.hpp"
#include "egpt/events_io.hpp"
#include "egpt/feature_transform.hpp"
#include "egpt/optical_flow.hpp"
#include "egpt/rgbd.hpp"

namespace {

// Shi–Tomasi style corner selection on a grid (replaces the external
// detector the reference assumes upstream of OpticalFlow.cpp).
std::vector<egpt::Vec2> DetectFeatures(const egpt::GrayImage& img, int max_feats,
                                       int cell = 24, int border = 12) {
  std::vector<std::pair<double, egpt::Vec2>> scored;
  for (int cy = border; cy + cell < img.height - border; cy += cell) {
    for (int cx = border; cx + cell < img.width - border; cx += cell) {
      double best = 0;
      egpt::Vec2 best_pt;
      for (int y = cy; y < cy + cell; y += 2) {
        for (int x = cx; x < cx + cell; x += 2) {
          // Structure tensor summed over a 5x5 window (a single pixel's
          // tensor is rank-1 and its min eigenvalue is always zero).
          double a = 0, b = 0, c = 0;
          for (int wy = -2; wy <= 2; ++wy)
            for (int wx = -2; wx <= 2; ++wx) {
              const double ix =
                  0.5 * (img.at(x + wx + 1, y + wy) - img.at(x + wx - 1, y + wy));
              const double iy =
                  0.5 * (img.at(x + wx, y + wy + 1) - img.at(x + wx, y + wy - 1));
              a += ix * ix;
              b += ix * iy;
              c += iy * iy;
            }
          const double tr = a + c;
          const double det = a * c - b * b;
          const double min_eig = 0.5 * (tr - std::sqrt(std::max(tr * tr - 4 * det, 0.0)));
          if (min_eig > best) {
            best = min_eig;
            best_pt = {static_cast<double>(x), static_cast<double>(y)};
          }
        }
      }
      if (best > 25.0) scored.push_back({best, best_pt});
    }
  }
  std::sort(scored.begin(), scored.end(),
            [](const auto& a, const auto& b) { return a.first > b.first; });
  std::vector<egpt::Vec2> out;
  for (const auto& [s, p] : scored) {
    out.push_back(p);
    if (static_cast<int>(out.size()) >= max_feats) break;
  }
  return out;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::cerr << "usage: egpt_feature_track <config.yaml> <out.csv>\n";
    return 2;
  }
  const auto cfg = egpt::Config::Load(argv[1]);
  if (!cfg) {
    std::cerr << "cannot read config " << argv[1] << "\n";
    return 1;
  }
  const std::string npy_dir = argc > 3 ? argv[3] : "";
  const auto cam_rgb = cfg->get_camera("rgb");
  const auto cam_event = cfg->get_camera("event");
  if (!cam_rgb || !cam_event) {
    std::cerr << "config must define rgb_* and event_* camera blocks\n";
    return 1;
  }
  const std::string data = cfg->get_str("data_path").value_or(".");
  const int num_frames = static_cast<int>(cfg->get_double("num_frames").value_or(2));
  const double frame_dt = cfg->get_double("frame_dt").value_or(1.0 / 30);
  const int window = static_cast<int>(cfg->get_double("event_window").value_or(11));

  egpt::EventsDataIO events_io;
  const std::string events_path = data + "/events.npy";
  const bool have_events = events_io.GoOfflineNpy(events_path);

  std::ofstream out(argv[2]);
  out << "frame,id,t0,t1,prev_x,prev_y,cur_x,cur_y,event_x,event_y,n_events_window\n";

  egpt::GrayImage prev_img;
  std::vector<egpt::Event> popped;
  char namebuf[512];

  for (int fi = 0; fi < num_frames; ++fi) {
    std::snprintf(namebuf, sizeof(namebuf), "%s/frame_%06d.ppm", data.c_str(), fi);
    std::vector<uint8_t> rgb;
    int w, h;
    if (!egpt::ReadRgbPpm(namebuf, rgb, w, h)) {
      std::cerr << "missing " << namebuf << "\n";
      break;
    }
    egpt::GrayImage img{egpt::RgbToGray(rgb, w, h), w, h};

    std::snprintf(namebuf, sizeof(namebuf), "%s/depth_%06d.pgm", data.c_str(), fi);
    const auto depth = egpt::ReadDepthPgm(namebuf);

    if (have_events) {
      popped.clear();
      // Blocking form: the offline producer thread may not have reached
      // this frame's horizon yet (the non-blocking pop is live-stream
      // semantics and would silently emit an empty window).
      events_io.PopDataUntilBlocking((fi + 1) * frame_dt, popped);
      // This pop covers (fi*dt, (fi+1)*dt] — the motion interval of the
      // NEXT frame's track rows (row frame=fi+1 records t0=fi*dt,
      // t1=(fi+1)*dt), so the window is saved under fi+1. Saving it under
      // fi would pair every training sample with the events AFTER its
      // labeled motion.
      if (!npy_dir.empty() && fi + 1 < num_frames) {
        std::snprintf(namebuf, sizeof(namebuf), "%s/events_%06d.npy",
                      npy_dir.c_str(), fi + 1);
        if (!egpt::SaveEventsNpy(namebuf, popped)) {
          std::cerr << "cannot write " << namebuf << "\n";
          return 1;
        }
      }
    }

    if (fi > 0 && depth) {
      const auto feats = DetectFeatures(prev_img, 200);
      const auto tracked = egpt::PerformMatching(prev_img, img, feats, *cam_rgb);

      std::vector<egpt::FeaturePoint> fps;
      for (size_t i = 0; i < tracked.size(); ++i) {
        if (!tracked[i].valid) continue;
        egpt::FeaturePoint fp;
        fp.id = static_cast<int>(i);
        fp.px = tracked[i].cur;
        fps.push_back(fp);
      }
      const auto proj = egpt::ProjectFeatures(fps, *cam_rgb, *cam_event, *depth);

      for (size_t i = 0; i < fps.size(); ++i) {
        if (!proj.points[i].valid) continue;
        const auto& ev_px = proj.points[i].px;
        int n_win = 0;
        const double half = window / 2.0;
        for (const auto& e : popped) {
          if (std::abs(e.x - ev_px.x) <= half && std::abs(e.y - ev_px.y) <= half)
            ++n_win;
        }
        const auto& tr = tracked[fps[i].id];
        out << fi << ',' << fps[i].id << ',' << (fi - 1) * frame_dt << ','
            << fi * frame_dt << ',' << tr.prev.x << ',' << tr.prev.y << ','
            << tr.cur.x << ',' << tr.cur.y << ',' << ev_px.x << ',' << ev_px.y
            << ',' << n_win << '\n';
      }
    }
    prev_img = std::move(img);
  }
  events_io.Stop();
  return 0;
}
