#include "egpt/optical_flow.hpp"

#include <algorithm>
#include <array>
#include <cmath>
#include <random>

namespace egpt {

float GrayImage::sample(double x, double y) const {
  x = std::clamp(x, 0.0, static_cast<double>(width - 1));
  y = std::clamp(y, 0.0, static_cast<double>(height - 1));
  const int x0 = static_cast<int>(x), y0 = static_cast<int>(y);
  const int x1 = std::min(x0 + 1, width - 1), y1 = std::min(y0 + 1, height - 1);
  const double fx = x - x0, fy = y - y0;
  return static_cast<float>(
      at(x0, y0) * (1 - fx) * (1 - fy) + at(x1, y0) * fx * (1 - fy) +
      at(x0, y1) * (1 - fx) * fy + at(x1, y1) * fx * fy);
}

GrayImage GrayImage::downsample2() const {
  GrayImage out;
  out.width = width / 2;
  out.height = height / 2;
  out.data.resize(static_cast<size_t>(out.width) * out.height);
  for (int y = 0; y < out.height; ++y)
    for (int x = 0; x < out.width; ++x) {
      out.data[static_cast<size_t>(y) * out.width + x] =
          0.25f * (at(2 * x, 2 * y) + at(2 * x + 1, 2 * y) +
                   at(2 * x, 2 * y + 1) + at(2 * x + 1, 2 * y + 1));
    }
  return out;
}

namespace {

// Single-level iterative LK around an initial guess; returns refined point
// or nullopt if the normal matrix is degenerate / point leaves the image.
std::optional<Vec2> LKLevel(const GrayImage& prev, const GrayImage& cur,
                            const Vec2& p_prev, Vec2 guess, const KLTOptions& o) {
  const int r = o.window_radius;
  // Spatial gradient (Scharr-free central differences) and template values.
  const int n = (2 * r + 1) * (2 * r + 1);
  std::vector<float> tmpl(n), gx(n), gy(n);
  int idx = 0;
  double a11 = 0, a12 = 0, a22 = 0;
  for (int dy = -r; dy <= r; ++dy)
    for (int dx = -r; dx <= r; ++dx, ++idx) {
      const double x = p_prev.x + dx, y = p_prev.y + dy;
      tmpl[idx] = prev.sample(x, y);
      const float ix = static_cast<float>(
          0.5 * (prev.sample(x + 1, y) - prev.sample(x - 1, y)));
      const float iy = static_cast<float>(
          0.5 * (prev.sample(x, y + 1) - prev.sample(x, y - 1)));
      gx[idx] = ix;
      gy[idx] = iy;
      a11 += ix * ix;
      a12 += ix * iy;
      a22 += iy * iy;
    }
  const double det = a11 * a22 - a12 * a12;
  const double tr = a11 + a22;
  const double min_eig = 0.5 * (tr - std::sqrt(std::max(tr * tr - 4 * det, 0.0)));
  if (min_eig / n < o.min_eigen || det <= 0) return std::nullopt;

  for (int it = 0; it < o.max_iters; ++it) {
    double b1 = 0, b2 = 0;
    idx = 0;
    for (int dy = -r; dy <= r; ++dy)
      for (int dx = -r; dx <= r; ++dx, ++idx) {
        const float diff =
            cur.sample(guess.x + dx, guess.y + dy) - tmpl[idx];
        b1 += diff * gx[idx];
        b2 += diff * gy[idx];
      }
    const double vx = -(a22 * b1 - a12 * b2) / det;
    const double vy = -(-a12 * b1 + a11 * b2) / det;
    guess.x += vx;
    guess.y += vy;
    if (std::sqrt(vx * vx + vy * vy) < o.epsilon) break;
  }
  if (guess.x < 0 || guess.y < 0 || guess.x >= cur.width || guess.y >= cur.height)
    return std::nullopt;
  return guess;
}

std::optional<Vec2> LKPyramidal(const std::vector<GrayImage>& pyr_prev,
                                const std::vector<GrayImage>& pyr_cur,
                                const Vec2& p, const KLTOptions& o) {
  const int levels = static_cast<int>(pyr_prev.size());
  const double top_scale = std::pow(0.5, levels - 1);
  Vec2 guess{p.x * top_scale, p.y * top_scale};
  for (int lv = levels - 1; lv >= 0; --lv) {
    const double s = std::pow(0.5, lv);
    const Vec2 p_lv{p.x * s, p.y * s};
    auto refined = LKLevel(pyr_prev[lv], pyr_cur[lv], p_lv, guess, o);
    if (!refined) return std::nullopt;
    guess = *refined;
    if (lv > 0) guess = guess * 2.0;
  }
  return guess;
}

std::vector<GrayImage> BuildPyramid(const GrayImage& img, int levels) {
  std::vector<GrayImage> pyr{img};
  for (int i = 1; i < levels; ++i) {
    if (pyr.back().width < 16 || pyr.back().height < 16) break;
    pyr.push_back(pyr.back().downsample2());
  }
  return pyr;
}

// Symmetric Jacobi eigen-decomposition for the 9x9 normal matrix of the
// 8-point algorithm (smallest-eigenvector extraction, no external LA).
void JacobiEigen9(std::array<double, 81>& A, std::array<double, 81>& V) {
  for (int i = 0; i < 81; ++i) V[i] = 0;
  for (int i = 0; i < 9; ++i) V[i * 9 + i] = 1;
  for (int sweep = 0; sweep < 50; ++sweep) {
    double off = 0;
    for (int p = 0; p < 9; ++p)
      for (int q = p + 1; q < 9; ++q) off += A[p * 9 + q] * A[p * 9 + q];
    if (off < 1e-18) break;
    for (int p = 0; p < 9; ++p)
      for (int q = p + 1; q < 9; ++q) {
        const double apq = A[p * 9 + q];
        if (std::abs(apq) < 1e-18) continue;
        const double app = A[p * 9 + p], aqq = A[q * 9 + q];
        const double theta = 0.5 * (aqq - app) / apq;
        const double t = (theta >= 0 ? 1.0 : -1.0) /
                         (std::abs(theta) + std::sqrt(theta * theta + 1));
        const double c = 1.0 / std::sqrt(t * t + 1), s = t * c;
        for (int k = 0; k < 9; ++k) {
          const double akp = A[k * 9 + p], akq = A[k * 9 + q];
          A[k * 9 + p] = c * akp - s * akq;
          A[k * 9 + q] = s * akp + c * akq;
        }
        for (int k = 0; k < 9; ++k) {
          const double apk = A[p * 9 + k], aqk = A[q * 9 + k];
          A[p * 9 + k] = c * apk - s * aqk;
          A[q * 9 + k] = s * apk + c * aqk;
        }
        for (int k = 0; k < 9; ++k) {
          const double vkp = V[k * 9 + p], vkq = V[k * 9 + q];
          V[k * 9 + p] = c * vkp - s * vkq;
          V[k * 9 + q] = s * vkp + c * vkq;
        }
      }
  }
}

// 8-point fundamental matrix from >=8 normalized correspondences.
std::optional<Mat3> EightPoint(const std::vector<Vec2>& p0,
                               const std::vector<Vec2>& p1,
                               const std::vector<int>& idxs) {
  std::array<double, 81> AtA{};
  for (int i : idxs) {
    const double u = p0[i].x, v = p0[i].y, up = p1[i].x, vp = p1[i].y;
    const double row[9] = {up * u, up * v, up, vp * u, vp * v, vp, u, v, 1};
    for (int a = 0; a < 9; ++a)
      for (int b = 0; b < 9; ++b) AtA[a * 9 + b] += row[a] * row[b];
  }
  std::array<double, 81> V{};
  JacobiEigen9(AtA, V);
  // Smallest eigenvalue's eigenvector.
  int best = 0;
  double best_val = AtA[0];
  for (int i = 1; i < 9; ++i)
    if (AtA[i * 9 + i] < best_val) {
      best_val = AtA[i * 9 + i];
      best = i;
    }
  Mat3 F;
  for (int i = 0; i < 9; ++i) F.m[i] = V[i * 9 + best];
  return F;
}

double SampsonError(const Mat3& F, const Vec2& p0, const Vec2& p1) {
  const Vec3 x0{p0.x, p0.y, 1}, x1{p1.x, p1.y, 1};
  const Vec3 Fx0 = F * x0;
  const Vec3 Ftx1 = F.transpose() * x1;
  const double num = x1.dot(Fx0);
  const double den = Fx0.x * Fx0.x + Fx0.y * Fx0.y + Ftx1.x * Ftx1.x + Ftx1.y * Ftx1.y;
  if (den < 1e-18) return 1e18;
  return num * num / den;
}

}  // namespace

std::vector<TrackedPoint> TrackKLT(const GrayImage& prev, const GrayImage& cur,
                                   const std::vector<Vec2>& points,
                                   const KLTOptions& opts) {
  const auto pyr_prev = BuildPyramid(prev, opts.pyramid_levels);
  const auto pyr_cur = BuildPyramid(cur, opts.pyramid_levels);
  std::vector<TrackedPoint> out(points.size());
  for (size_t i = 0; i < points.size(); ++i) {
    out[i].prev = points[i];
    auto fwd = LKPyramidal(pyr_prev, pyr_cur, points[i], opts);
    if (!fwd) continue;
    // Forward-backward consistency (OpticalFlow.cpp:28-41).
    auto bwd = LKPyramidal(pyr_cur, pyr_prev, *fwd, opts);
    if (!bwd || (*bwd - points[i]).norm() > opts.fb_threshold) continue;
    out[i].cur = *fwd;
    out[i].valid = true;
  }
  return out;
}

std::vector<bool> RansacFundamental(const std::vector<Vec2>& p0,
                                    const std::vector<Vec2>& p1,
                                    double focal,
                                    const RansacOptions& opts) {
  const size_t n = p0.size();
  std::vector<bool> inliers(n, false);
  if (n < 8) return inliers;
  const double thresh = opts.threshold_px / focal;  // OpticalFlow.cpp:62
  const double thresh2 = thresh * thresh;

  std::mt19937_64 rng(opts.seed);
  std::uniform_int_distribution<size_t> dist(0, n - 1);
  int best_count = 0;
  std::vector<bool> best(n, false);

  for (int it = 0; it < opts.iterations; ++it) {
    std::vector<int> sample;
    while (sample.size() < 8) {
      const int c = static_cast<int>(dist(rng));
      if (std::find(sample.begin(), sample.end(), c) == sample.end())
        sample.push_back(c);
    }
    auto F = EightPoint(p0, p1, sample);
    if (!F) continue;
    int count = 0;
    std::vector<bool> cur(n, false);
    for (size_t i = 0; i < n; ++i) {
      if (SampsonError(*F, p0[i], p1[i]) < thresh2) {
        cur[i] = true;
        ++count;
      }
    }
    if (count > best_count) {
      best_count = count;
      best = cur;
    }
  }
  // Final refit on all inliers for stability.
  if (best_count >= 8) {
    std::vector<int> all;
    for (size_t i = 0; i < n; ++i)
      if (best[i]) all.push_back(static_cast<int>(i));
    if (auto F = EightPoint(p0, p1, all)) {
      for (size_t i = 0; i < n; ++i)
        best[i] = SampsonError(*F, p0[i], p1[i]) < thresh2;
    }
  }
  return best;
}

std::vector<TrackedPoint> PerformMatching(const GrayImage& prev, const GrayImage& cur,
                                          const std::vector<Vec2>& points,
                                          const RadtanCamera& cam,
                                          const KLTOptions& klt,
                                          const RansacOptions& ransac) {
  auto tracked = TrackKLT(prev, cur, points, klt);

  // Collect valid matches in normalized coordinates (OpticalFlow.cpp:44-58).
  std::vector<Vec2> n0, n1;
  std::vector<size_t> map;
  for (size_t i = 0; i < tracked.size(); ++i) {
    if (!tracked[i].valid) continue;
    const Vec3 c0 = cam.pixel_to_camera(tracked[i].prev);
    const Vec3 c1 = cam.pixel_to_camera(tracked[i].cur);
    n0.push_back({c0.x, c0.y});
    n1.push_back({c1.x, c1.y});
    map.push_back(i);
  }
  const double focal = std::max(cam.K.fx, cam.K.fy);
  const auto inl = RansacFundamental(n0, n1, focal, ransac);
  for (size_t j = 0; j < map.size(); ++j)
    if (!inl[j]) tracked[map[j]].valid = false;
  return tracked;
}

}  // namespace egpt
