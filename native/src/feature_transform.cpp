#include "egpt/feature_transform.hpp"

namespace egpt {

TransformResult ProjectFeatures(const std::vector<FeaturePoint>& features,
                                const RadtanCamera& cam_src,
                                const RadtanCamera& cam_dst,
                                const DepthMap& depth_src,
                                double depth_scale,
                                double border_margin) {
  TransformResult result;
  result.points.reserve(features.size());
  const SE3 T_dst_src = cam_dst.T_base_cam.inverse() * cam_src.T_base_cam;

  for (const auto& f : features) {
    FeaturePoint out;
    out.id = f.id;
    // 1. Depth at the (distorted) source pixel, bilinear with valid-neighbor
    //    weighting (FeatureTransform.cpp:16-41); fallback to window minimum.
    auto d = depth_src.bilinear(f.px);
    if (!d) d = depth_src.min_in_range(f.px, 2);
    if (!d || *d <= 0) {
      result.points.push_back(out);
      continue;
    }
    const double depth_m = *d * depth_scale;

    // 2. Undistort + back-project to a 3D point in the source camera frame.
    const Vec3 p_src = cam_src.pixel_to_camera(f.px, depth_m);

    // 3. SE3 into the destination camera frame (CamBase.h:558-560).
    const Vec3 p_dst = T_dst_src * p_src;

    // 4. Project + re-distort; reject behind-camera and out-of-bounds
    //    (FeatureTransform.cpp validity filtering).
    const auto px_dst = cam_dst.camera_to_pixel(p_dst);
    if (px_dst && cam_dst.K.in_bounds(*px_dst, border_margin)) {
      out.px = *px_dst;
      out.valid = true;
      ++result.num_valid;
    }
    result.points.push_back(out);
  }
  return result;
}

}  // namespace egpt
