#include "egpt/raster.hpp"

#include <algorithm>
#include <cstring>

namespace egpt {

void RasterizeEvents(const uint16_t* x, const uint16_t* y, const uint8_t* p,
                     size_t n, int height, int width, uint8_t* out) {
  std::memset(out, 255, static_cast<size_t>(height) * width * 3);
  // Sequential overwrite IS last-write-wins; one linear pass, cache-friendly.
  for (size_t i = 0; i < n; ++i) {
    if (x[i] >= width || y[i] >= height) continue;
    uint8_t* px = out + (static_cast<size_t>(y[i]) * width + x[i]) * 3;
    if (p[i] != 0) {        // red
      px[0] = 255; px[1] = 0; px[2] = 0;
    } else {                // blue
      px[0] = 0; px[1] = 0; px[2] = 255;
    }
  }
}

std::vector<uint8_t> RasterizeEvents(const std::vector<Event>& events,
                                     int& height, int& width) {
  if (height <= 0 || width <= 0) {
    int max_x = 0, max_y = 0;
    for (const auto& e : events) {
      max_x = std::max<int>(max_x, e.x);
      max_y = std::max<int>(max_y, e.y);
    }
    width = max_x + 1;
    height = max_y + 1;
  }
  std::vector<uint8_t> out(static_cast<size_t>(height) * width * 3, 255);
  for (const auto& e : events) {
    if (e.x >= width || e.y >= height) continue;
    uint8_t* px = out.data() + (static_cast<size_t>(e.y) * width + e.x) * 3;
    if (e.p != 0) { px[0] = 255; px[1] = 0; px[2] = 0; }
    else          { px[0] = 0;   px[1] = 0; px[2] = 255; }
  }
  return out;
}

std::vector<std::pair<size_t, size_t>> SplitByCount(size_t total, int n) {
  std::vector<std::pair<size_t, size_t>> out;
  const size_t per = total / static_cast<size_t>(n);
  for (int i = 0; i < n; ++i) {
    const size_t lo = static_cast<size_t>(i) * per;
    const size_t hi = (i == n - 1) ? total : lo + per;
    out.emplace_back(lo, hi);
  }
  return out;
}

}  // namespace egpt
