// Assert-style unit tests for the native toolchain (no framework dep).
#include <cassert>
#include <cmath>
#include <cstdio>
#include <fstream>
#include <iostream>
#include <random>
#include <chrono>
#include <thread>

#include "egpt/camera.hpp"
#include "egpt/config.hpp"
#include "egpt/events_io.hpp"
#include "egpt/feature_transform.hpp"
#include "egpt/optical_flow.hpp"
#include "egpt/raster.hpp"
#include "egpt/rgbd.hpp"

using namespace egpt;

static int failures = 0;
#define CHECK(cond)                                                       \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__ << "  " #cond   \
                << "\n";                                                  \
      ++failures;                                                         \
    }                                                                     \
  } while (0)

#define CHECK_NEAR(a, b, tol) CHECK(std::abs((a) - (b)) < (tol))

static void TestMath() {
  const SE3 T = SE3::from_quat_trans(0.1, 0.2, 0.3, 0.9, {1, 2, 3});
  const Vec3 p{0.5, -0.2, 2.0};
  const Vec3 q = T.inverse() * (T * p);
  CHECK_NEAR(q.x, p.x, 1e-12);
  CHECK_NEAR(q.y, p.y, 1e-12);
  CHECK_NEAR(q.z, p.z, 1e-12);

  const Mat3 R = T.rotation();
  const Mat3 I = R * R.transpose();
  CHECK_NEAR(I(0, 0), 1, 1e-12);
  CHECK_NEAR(I(0, 1), 0, 1e-12);
  CHECK_NEAR(R.det(), 1, 1e-12);

  const Mat3 Rinv = R.inverse();
  const Mat3 I2 = R * Rinv;
  CHECK_NEAR(I2(1, 1), 1, 1e-12);

  // Composition consistency.
  const SE3 A = SE3::from_quat_trans(0, 0, 0.3826834, 0.9238795, {1, 0, 0});
  const SE3 B = SE3::from_quat_trans(0.2, -0.1, 0, 0.97, {0, 1, 0});
  const Vec3 via_compose = (A * B) * p;
  const Vec3 via_seq = A * (B * p);
  CHECK_NEAR(via_compose.x, via_seq.x, 1e-9);
  CHECK_NEAR(via_compose.z, via_seq.z, 1e-9);
}

static void TestCamera() {
  RadtanCamera cam;
  cam.K = {400, 400, 320, 240, 640, 480};
  cam.D = {-0.3, 0.1, 1e-4, -2e-4, 0.01};

  // distort/undistort roundtrip over the frame.
  for (double u = 40; u < 600; u += 100) {
    for (double v = 40; v < 440; v += 80) {
      const Vec2 n = cam.K.pixel_to_normalized({u, v});
      const Vec2 d = cam.D.distort(n);
      const Vec2 n2 = cam.D.undistort(d);
      CHECK_NEAR(n2.x, n.x, 1e-9);
      CHECK_NEAR(n2.y, n.y, 1e-9);
    }
  }

  // pixel -> camera -> pixel roundtrip.
  const Vec2 px{123.0, 321.0};
  const Vec3 pc = cam.pixel_to_camera(px, 2.5);
  const auto px2 = cam.camera_to_pixel(pc);
  CHECK(px2.has_value());
  CHECK_NEAR(px2->x, px.x, 1e-6);
  CHECK_NEAR(px2->y, px.y, 1e-6);

  // Behind camera rejected.
  CHECK(!cam.camera_to_pixel({0, 0, -1}).has_value());

  // Jacobian vs finite differences.
  const Vec2 n{0.2, -0.3};
  double J[4];
  cam.D.jacobian(n, J);
  const double eps = 1e-7;
  const Vec2 dx = (cam.D.distort({n.x + eps, n.y}) - cam.D.distort({n.x - eps, n.y})) * (0.5 / eps);
  const Vec2 dy = (cam.D.distort({n.x, n.y + eps}) - cam.D.distort({n.x, n.y - eps})) * (0.5 / eps);
  CHECK_NEAR(J[0], dx.x, 1e-5);
  CHECK_NEAR(J[2], dx.y, 1e-5);
  CHECK_NEAR(J[1], dy.x, 1e-5);
  CHECK_NEAR(J[3], dy.y, 1e-5);
}

static void TestDepthMap() {
  std::vector<float> d(16, 0.f);
  d[5] = 2.0f;  // (1,1)
  d[6] = 4.0f;  // (2,1)
  d[9] = 2.0f;  // (1,2)
  d[10] = 4.0f; // (2,2)
  DepthMap dm(d, 4, 4);
  auto b = dm.bilinear({1.5, 1.5});
  CHECK(b && std::abs(*b - 3.0) < 1e-9);
  // Invalid-neighbor weighting: (0.5, 1.0) mixes valid (1,1) with invalid
  // (0,1) -> falls back to the valid one only.
  auto b2 = dm.bilinear({0.5, 1.0});
  CHECK(b2 && std::abs(*b2 - 2.0) < 1e-9);
  auto m = dm.min_in_range({2, 2}, 1);
  CHECK(m && *m == 2.0);
  CHECK(!dm.bilinear({-1, -1}).has_value());
}

static void TestEventsQueue() {
  EventsDataIO io;
  EventPacket p1;
  for (int i = 0; i < 10; ++i) p1.events.push_back({i * 0.001, uint16_t(i), 0, 1});
  p1.t_begin = 0;
  p1.t_end = 0.009;
  io.PushData(std::move(p1));

  std::vector<Event> out;
  // Horizon splits the packet: events at t <= 0.0045 are 0..4.
  const size_t n = io.PopDataUntil(0.0045, out);
  CHECK(n == 5);
  CHECK(io.queue_size() == 1);
  out.clear();
  io.PopDataUntil(1.0, out);
  CHECK(out.size() == 5);
  CHECK(out.front().x == 5);
  CHECK(io.queue_size() == 0);
}

static void TestEventsThreaded() {
  // Producer thread via a temp txt file.
  const char* path = "/tmp/egpt_test_events.txt";
  {
    std::ofstream f(path);
    for (int i = 0; i < 5000; ++i)
      f << 1000000 + i * 10 << " " << (i % 640) << " " << (i % 480) << " "
        << (i % 2) << "\n";
  }
  EventsDataIO io({/*packet_us=*/1000.0, /*paced=*/false});
  CHECK(io.GoOfflineTxt(path));
  std::vector<Event> out;
  // Drain until the producer finishes or a 10 s deadline passes.
  const auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(10);
  while (out.size() < 5000 && std::chrono::steady_clock::now() < deadline) {
    io.PopDataUntil(1e9, out);
    if (out.size() < 5000)
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  CHECK(out.size() == 5000);
  CHECK_NEAR(out[1].t - out[0].t, 10e-6, 1e-9);  // µs auto-detect
  io.Stop();
  std::remove(path);
}

static void TestRaster() {
  std::vector<Event> ev = {
      {0.0, 1, 0, 1},  // red at (1,0)
      {0.0, 0, 1, 0},  // blue at (0,1)
      {0.0, 1, 0, 0},  // overwrites (1,0) -> blue (last wins)
  };
  int h = 2, w = 2;
  auto frame = RasterizeEvents(ev, h, w);
  CHECK(h == 2 && w == 2);
  // (0,0) untouched white.
  CHECK(frame[0] == 255 && frame[1] == 255 && frame[2] == 255);
  // (1,0) blue.
  CHECK(frame[3] == 0 && frame[5] == 255);
  // (0,1) blue.
  CHECK(frame[w * 3 + 0] == 0 && frame[w * 3 + 2] == 255);

  auto splits = SplitByCount(10, 3);
  CHECK(splits.size() == 3);
  CHECK(splits[0].first == 0 && splits[0].second == 3);
  CHECK(splits[2].first == 6 && splits[2].second == 10);
}

static void TestNpyLoader() {
  // Generate a structured {x,y,t,p} npy by hand (the toolchain's on-disk
  // schema; note the reference's sample1.npy is a *pickled dict* readable
  // only from Python — the ctypes path passes arrays directly instead).
  const char* path = "/tmp/egpt_test_events.npy";
  {
    std::string header =
        "{'descr': [('x', '<u2'), ('y', '<u2'), ('t', '<u4'), ('p', '<u1')], "
        "'fortran_order': False, 'shape': (3,), }";
    while ((10 + header.size() + 1) % 64 != 0) header += ' ';
    header += '\n';
    std::ofstream f(path, std::ios::binary);
    f.write("\x93NUMPY\x01\x00", 8);
    const uint16_t hlen = static_cast<uint16_t>(header.size());
    f.write(reinterpret_cast<const char*>(&hlen), 2);
    f.write(header.data(), static_cast<std::streamsize>(header.size()));
    struct __attribute__((packed)) Rec { uint16_t x, y; uint32_t t; uint8_t p; };
    const Rec recs[3] = {{10, 20, 100, 1}, {11, 21, 200, 0}, {12, 22, 350, 1}};
    f.write(reinterpret_cast<const char*>(recs), sizeof(recs));
  }
  std::vector<Event> ev;
  CHECK(LoadEventsNpy(path, ev));
  CHECK(ev.size() == 3);
  if (ev.size() == 3) {
    CHECK(ev[0].x == 10 && ev[0].y == 20 && ev[0].p == 1);
    CHECK_NEAR(ev[2].t, 350e-6, 1e-12);
  }
  std::remove(path);
}

static void TestNpyWriterRoundtripAndBlockingPop() {
  // SaveEventsNpy -> LoadEventsNpy round trip, then the offline-mode
  // blocking pop: an immediate PopDataUntilBlocking after GoOfflineNpy
  // must see every event up to the horizon (the non-blocking pop races
  // the producer thread and can return an empty window).
  const char* path = "/tmp/egpt_test_events_rt.npy";
  std::vector<Event> src;
  for (int i = 0; i < 5000; ++i) {
    Event e;
    e.t = i * 1e-5;  // 0 .. 50 ms
    e.x = static_cast<uint16_t>(i % 320);
    e.y = static_cast<uint16_t>(i % 240);
    e.p = static_cast<uint8_t>(i % 2);
    src.push_back(e);
  }
  CHECK(SaveEventsNpy(path, src));
  std::vector<Event> back;
  CHECK(LoadEventsNpy(path, back));
  CHECK(back.size() == src.size());
  if (back.size() == src.size()) {
    CHECK(back[4999].x == src[4999].x && back[4999].p == src[4999].p);
    CHECK_NEAR(back[4999].t, src[4999].t, 1e-9);
  }

  EventsDataIO io;
  CHECK(io.GoOfflineNpy(path));
  std::vector<Event> first, rest;
  io.PopDataUntilBlocking(0.025, first);   // immediately: must not race
  CHECK(first.size() >= 2400 && first.size() <= 2600);
  io.PopDataUntilBlocking(1.0, rest);      // past stream end: drains all
  CHECK(first.size() + rest.size() == src.size());
  io.Stop();
  std::remove(path);
}

static void TestConfig() {
  const std::string yaml =
      "# rig config\n"
      "data_path: /tmp/data\n"
      "rgb_intrinsics: [390.0, 390.5, 320.1, 241.9]\n"
      "rgb_distortion: [-0.05, 0.06, 0.0001, -0.0002]\n"
      "rgb_resolution: [640, 480]\n"
      "rgb_T_base_cam: 0 0 0 1 0.01 0.02 0.03\n"
      "event_intrinsics: [550, 551, 170, 130]\n"
      "event_resolution: [346, 260]\n";
  Config cfg = Config::Parse(yaml);
  CHECK(cfg.get_str("data_path").value() == "/tmp/data");
  auto cam = cfg.get_camera("rgb");
  CHECK(cam.has_value());
  CHECK_NEAR(cam->K.fy, 390.5, 1e-12);
  CHECK_NEAR(cam->D.k2, 0.06, 1e-12);
  CHECK_NEAR(cam->T_base_cam.t.z, 0.03, 1e-12);
  auto ev = cfg.get_camera("event");
  CHECK(ev && ev->K.width == 346 && ev->D.k1 == 0.0);
  CHECK(!cfg.get_camera("depth").has_value());
}

static GrayImage SyntheticImage(int w, int h, double shift_x, double shift_y) {
  GrayImage img;
  img.width = w;
  img.height = h;
  img.data.resize(static_cast<size_t>(w) * h);
  // Smooth random blobs -> trackable texture.
  for (int y = 0; y < h; ++y)
    for (int x = 0; x < w; ++x) {
      const double xs = x - shift_x, ys = y - shift_y;
      double v = 120 + 60 * std::sin(xs * 0.12) * std::cos(ys * 0.09) +
                 40 * std::sin(xs * 0.031 + ys * 0.045);
      img.data[static_cast<size_t>(y) * w + x] = static_cast<float>(v);
    }
  return img;
}

static void TestKLT() {
  const double dx = 3.7, dy = -2.2;
  const auto prev = SyntheticImage(160, 120, 0, 0);
  const auto cur = SyntheticImage(160, 120, dx, dy);
  std::vector<Vec2> pts;
  for (double y = 30; y < 100; y += 15)
    for (double x = 30; x < 140; x += 15) pts.push_back({x, y});
  const auto tracked = TrackKLT(prev, cur, pts);
  int valid = 0;
  double err = 0;
  for (const auto& t : tracked) {
    if (!t.valid) continue;
    ++valid;
    err += std::abs(t.cur.x - t.prev.x - dx) + std::abs(t.cur.y - t.prev.y - dy);
  }
  CHECK(valid > static_cast<int>(pts.size()) * 3 / 4);
  CHECK(err / std::max(valid, 1) < 0.1);
}

static void TestRansac() {
  // Matches consistent with a pure-translation epipolar geometry + outliers.
  std::mt19937_64 rng(7);
  std::uniform_real_distribution<double> U(-0.5, 0.5);
  std::vector<Vec2> p0, p1;
  const Vec3 t{0.2, 0.05, 1.0};  // camera translation
  for (int i = 0; i < 60; ++i) {
    const Vec3 X{U(rng) * 4, U(rng) * 4, 4 + 2 * U(rng)};
    p0.push_back({X.x / X.z, X.y / X.z});
    const Vec3 X2 = X - t;
    p1.push_back({X2.x / X2.z, X2.y / X2.z});
  }
  for (int i = 0; i < 15; ++i) {  // gross outliers
    p0.push_back({U(rng), U(rng)});
    p1.push_back({U(rng), U(rng)});
  }
  const auto inl = RansacFundamental(p0, p1, 400.0, {400, 1.0, 123});
  int in_true = 0, in_false = 0;
  for (int i = 0; i < 60; ++i) in_true += inl[i];
  for (int i = 60; i < 75; ++i) in_false += inl[i];
  CHECK(in_true > 50);
  CHECK(in_false < 5);
}

static void TestProjectDepthAndFeatures() {
  RadtanCamera cam_rgb;
  cam_rgb.K = {380, 380, 160, 120, 320, 240};
  RadtanCamera cam_ev;
  cam_ev.K = {300, 300, 160, 120, 320, 240};
  // Event cam 5 cm to the right of RGB.
  cam_ev.T_base_cam = SE3::from_quat_trans(0, 0, 0, 1, {0.05, 0, 0});

  // Flat wall at 2 m in the RGB frame.
  std::vector<float> d(320 * 240, 2.0f);
  DepthMap depth(d, 320, 240);

  const auto reproj = ProjectDepth(depth, cam_rgb, cam_ev);
  // Center of the event view should see the wall at ~2 m.
  CHECK_NEAR(reproj.at(160, 120), 2.0f, 1e-3);

  std::vector<FeaturePoint> feats;
  for (double x = 60; x < 280; x += 40) feats.push_back({0, {x, 120.0}, false});
  for (size_t i = 0; i < feats.size(); ++i) feats[i].id = static_cast<int>(i);
  const auto res = ProjectFeatures(feats, cam_rgb, cam_ev, depth);
  CHECK(res.num_valid >= static_cast<int>(feats.size()) - 1);
  // Analytic check: point at RGB center, wall z=2, baseline 0.05 m ->
  // event pixel x = cx + fx * (-0.05) / 2 = 160 - 7.5.
  FeaturePoint center{99, {160, 120}, false};
  const auto r2 = ProjectFeatures({center}, cam_rgb, cam_ev, depth);
  CHECK(r2.points[0].valid);
  CHECK_NEAR(r2.points[0].px.x, 160 - 300 * 0.05 / 2.0, 1e-6);
  CHECK_NEAR(r2.points[0].px.y, 120.0, 1e-6);
}

int main() {
  TestMath();
  TestCamera();
  TestDepthMap();
  TestEventsQueue();
  TestEventsThreaded();
  TestRaster();
  TestNpyLoader();
  TestNpyWriterRoundtripAndBlockingPop();
  TestConfig();
  TestKLT();
  TestRansac();
  TestProjectDepthAndFeatures();
  if (failures) {
    std::cerr << failures << " check(s) failed\n";
    return 1;
  }
  std::cout << "all native tests passed\n";
  return 0;
}
