// RGB-D replay + depth reprojection — the TPU-era RgbdDataIO<T>.
//
// Structural equivalent of preprocess/feature_track/RgbdDataIO.cpp with the
// camera SDKs (librealsense) and simulator (MuJoCo) replaced by file-backed
// replay: frames are read from disk, and the per-pixel KRK^-1 warp of the
// depth image into another camera's frame reproduces
// ProjectDepthToRgbAndEvent (RgbdDataIO.cpp:172-277) including the
// keep-minimum-depth z-buffer and pixel-footprint splatting.
#pragma once

#include <optional>
#include <string>
#include <vector>

#include "egpt/camera.hpp"

namespace egpt {

// Reproject a depth map from cam_src into cam_dst's pixel grid.
// Returns a dst-sized depth map; unobserved pixels are 0. Each source pixel
// footprint is splatted into the destination with a keep-min z-buffer
// (RgbdDataIO.cpp:172-277).
DepthMap ProjectDepth(const DepthMap& depth_src, const RadtanCamera& cam_src,
                      const RadtanCamera& cam_dst, double depth_scale = 1.0,
                      int splat_radius = 1);

// Minimal PGM (P5, 16-bit or 8-bit) depth reader and PPM (P6) RGB reader —
// the file-backed replacements for the RealSense frame queue.
std::optional<DepthMap> ReadDepthPgm(const std::string& path, double scale_to_m = 0.001);
bool ReadRgbPpm(const std::string& path, std::vector<uint8_t>& rgb, int& w, int& h);

// RGB -> grayscale float (for the KLT tracker).
std::vector<float> RgbToGray(const std::vector<uint8_t>& rgb, int w, int h);

}  // namespace egpt
