// Pinhole camera models for the native preprocessing toolchain.
//
// TPU-era equivalents of CamBase<T>/CamRadtan<T>
// (preprocess/feature_track/CamBase.h, CamRadtan.h): intrinsics management,
// Brown–Conrady radial-tangential distortion with analytic forward model and
// Newton-iteration undistortion (the reference delegates undistortion to
// cv::undistortPoints, which itself iterates), projective transforms between
// camera/pixel frames, depth lookup with bilinear interpolation and
// neighborhood fallback, and SE3 extrinsics between rig cameras.
#pragma once

#include <algorithm>
#include <cmath>
#include <optional>
#include <vector>

#include "egpt/math.hpp"

namespace egpt {

struct Intrinsics {
  double fx = 1, fy = 1, cx = 0, cy = 0;
  int width = 0, height = 0;

  Vec2 normalized_to_pixel(const Vec2& n) const {
    return {fx * n.x + cx, fy * n.y + cy};
  }
  Vec2 pixel_to_normalized(const Vec2& p) const {
    return {(p.x - cx) / fx, (p.y - cy) / fy};
  }
  bool in_bounds(const Vec2& p, double margin = 0.0) const {
    return p.x >= margin && p.y >= margin && p.x < width - margin && p.y < height - margin;
  }
};

// Brown–Conrady: k1 k2 p1 p2 k3 (OpenCV ordering, CamRadtan.h:88-139).
struct RadtanDistortion {
  double k1 = 0, k2 = 0, p1 = 0, p2 = 0, k3 = 0;

  Vec2 distort(const Vec2& n) const {
    const double x = n.x, y = n.y;
    const double r2 = x * x + y * y;
    const double radial = 1 + r2 * (k1 + r2 * (k2 + r2 * k3));
    return {x * radial + 2 * p1 * x * y + p2 * (r2 + 2 * x * x),
            y * radial + p1 * (r2 + 2 * y * y) + 2 * p2 * x * y};
  }

  // 2x2 Jacobian d(distorted)/d(normalized) (CamRadtan.h:147-190).
  void jacobian(const Vec2& n, double J[4]) const {
    const double x = n.x, y = n.y;
    const double r2 = x * x + y * y;
    const double radial = 1 + r2 * (k1 + r2 * (k2 + r2 * k3));
    const double dradial_dr2 = k1 + 2 * k2 * r2 + 3 * k3 * r2 * r2;
    J[0] = radial + x * (2 * x) * dradial_dr2 + 2 * p1 * y + 6 * p2 * x;
    J[1] = x * (2 * y) * dradial_dr2 + 2 * p1 * x + 2 * p2 * y;
    J[2] = y * (2 * x) * dradial_dr2 + 2 * p1 * x + 2 * p2 * y;
    J[3] = radial + y * (2 * y) * dradial_dr2 + 6 * p1 * y + 2 * p2 * x;
  }

  // Newton undistortion; converges in <6 iterations for realistic lenses.
  Vec2 undistort(const Vec2& d, int iters = 10) const {
    Vec2 n = d;
    for (int i = 0; i < iters; ++i) {
      const Vec2 e = distort(n) - d;
      double J[4];
      jacobian(n, J);
      const double det = J[0] * J[3] - J[1] * J[2];
      if (std::abs(det) < 1e-14) break;
      const double dx = (J[3] * e.x - J[1] * e.y) / det;
      const double dy = (-J[2] * e.x + J[0] * e.y) / det;
      n.x -= dx;
      n.y -= dy;
      if (std::abs(dx) + std::abs(dy) < 1e-12) break;
    }
    return n;
  }
};

class RadtanCamera {
 public:
  Intrinsics K;
  RadtanDistortion D;
  // Extrinsics: transform taking points in this camera's frame to rig/base
  // frame (CamBase.h:524-548 keeps Depth<->RGB<->Event<->IMU SE3 chains).
  SE3 T_base_cam = SE3::identity();

  // pixel (distorted) -> unit-depth camera ray (CamBase.h:585-646).
  Vec3 pixel_to_camera(const Vec2& px, double depth = 1.0) const {
    const Vec2 n = D.undistort(K.pixel_to_normalized(px));
    return {n.x * depth, n.y * depth, depth};
  }

  // camera point -> distorted pixel (CamBase.h:567-578). Fails behind camera.
  std::optional<Vec2> camera_to_pixel(const Vec3& p) const {
    if (p.z <= 1e-9) return std::nullopt;
    const Vec2 n{p.x / p.z, p.y / p.z};
    return K.normalized_to_pixel(D.distort(n));
  }

  // Direct pixel->pixel homography-style warp at fixed depth plane
  // (pixel2pixel KRK^-1, CamBase.h:656-660).
  std::optional<Vec2> pixel_to_pixel(const Vec2& px, double depth,
                                     const RadtanCamera& other) const {
    const Vec3 pc = pixel_to_camera(px, depth);
    const Vec3 pw = T_base_cam * pc;
    const Vec3 po = other.T_base_cam.inverse() * pw;
    return other.camera_to_pixel(po);
  }
};

// Depth map with bilinear lookup + neighborhood fallback
// (CamBase.h get_depth :331-373, get_min_depth_in_range :380-412).
class DepthMap {
 public:
  DepthMap(std::vector<float> data, int width, int height)
      : data_(std::move(data)), w_(width), h_(height) {}

  int width() const { return w_; }
  int height() const { return h_; }
  float at(int x, int y) const { return data_[static_cast<size_t>(y) * w_ + x]; }

  // Bilinear over valid (>0, finite) neighbors (FeatureTransform.cpp:16-41).
  std::optional<double> bilinear(const Vec2& p) const {
    const int x0 = static_cast<int>(std::floor(p.x));
    const int y0 = static_cast<int>(std::floor(p.y));
    if (x0 < 0 || y0 < 0 || x0 + 1 >= w_ || y0 + 1 >= h_) return std::nullopt;
    const double fx = p.x - x0, fy = p.y - y0;
    const float d00 = at(x0, y0), d10 = at(x0 + 1, y0);
    const float d01 = at(x0, y0 + 1), d11 = at(x0 + 1, y0 + 1);
    double wsum = 0, dsum = 0;
    auto acc = [&](float d, double w) {
      if (d > 0 && std::isfinite(d)) {
        wsum += w;
        dsum += w * d;
      }
    };
    acc(d00, (1 - fx) * (1 - fy));
    acc(d10, fx * (1 - fy));
    acc(d01, (1 - fx) * fy);
    acc(d11, fx * fy);
    if (wsum < 1e-9) return std::nullopt;
    return dsum / wsum;
  }

  // Minimum valid depth in a square window (get_min_depth_in_range).
  std::optional<double> min_in_range(const Vec2& center, int radius) const {
    const int cx = static_cast<int>(std::lround(center.x));
    const int cy = static_cast<int>(std::lround(center.y));
    double best = -1;
    for (int y = std::max(0, cy - radius); y <= std::min(h_ - 1, cy + radius); ++y)
      for (int x = std::max(0, cx - radius); x <= std::min(w_ - 1, cx + radius); ++x) {
        const float d = at(x, y);
        if (d > 0 && std::isfinite(d) && (best < 0 || d < best)) best = d;
      }
    if (best < 0) return std::nullopt;
    return best;
  }

 private:
  std::vector<float> data_;
  int w_, h_;
};

}  // namespace egpt
