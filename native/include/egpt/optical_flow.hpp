// Pyramidal Lucas–Kanade tracking with forward–backward consistency and
// RANSAC outlier rejection — the TPU-era TrackKLT<T>.
//
// Structural equivalent of preprocess/feature_track/OpticalFlow.cpp:2-70,
// reimplemented without OpenCV: image pyramids by 2x box downsampling,
// iterative LK per level with a square window, forward-backward check
// (<=0.5 px, OpticalFlow.cpp:28-41), and RANSAC on a fundamental matrix
// estimated by the normalized 8-point algorithm in normalized image
// coordinates with a focal-scaled inlier threshold (OpticalFlow.cpp:44-69).
#pragma once

#include <cstdint>
#include <optional>
#include <vector>

#include "egpt/camera.hpp"

namespace egpt {

// Grayscale float image, row-major.
struct GrayImage {
  std::vector<float> data;
  int width = 0, height = 0;

  float at(int x, int y) const { return data[static_cast<size_t>(y) * width + x]; }
  // Bilinear sample with border clamp.
  float sample(double x, double y) const;
  GrayImage downsample2() const;
};

struct KLTOptions {
  int pyramid_levels = 3;
  int window_radius = 7;       // 15x15 window
  int max_iters = 30;
  double epsilon = 0.01;       // convergence threshold (px)
  double fb_threshold = 0.5;   // forward-backward check (OpticalFlow.cpp:37)
  double min_eigen = 1e-4;     // conditioning floor for the 2x2 system
};

struct TrackedPoint {
  Vec2 prev, cur;
  bool valid = false;
};

// Track points from prev to cur. Returns one TrackedPoint per input.
std::vector<TrackedPoint> TrackKLT(const GrayImage& prev, const GrayImage& cur,
                                   const std::vector<Vec2>& points,
                                   const KLTOptions& opts = {});

struct RansacOptions {
  int iterations = 200;
  double threshold_px = 1.0;   // scaled by focal length into normalized coords
  uint64_t seed = 42;
};

// Fundamental-matrix RANSAC over matched normalized coordinates; marks
// inliers. ``focal`` scales threshold_px into normalized units
// (OpticalFlow.cpp:44-69 divides by max focal length).
std::vector<bool> RansacFundamental(const std::vector<Vec2>& pts0_norm,
                                    const std::vector<Vec2>& pts1_norm,
                                    double focal,
                                    const RansacOptions& opts = {});

// Full matching step: KLT + FB check + undistort-to-normalized + RANSAC,
// mirroring perform_matching (OpticalFlow.cpp:2-70).
std::vector<TrackedPoint> PerformMatching(const GrayImage& prev, const GrayImage& cur,
                                          const std::vector<Vec2>& points,
                                          const RadtanCamera& cam,
                                          const KLTOptions& klt = {},
                                          const RansacOptions& ransac = {});

}  // namespace egpt
