// Cross-camera feature reprojection — the TPU-era TrackBase<T> transfer.
//
// Structural equivalent of preprocess/feature_track/FeatureTransform.cpp:
// undistort pixel -> bilinear depth lookup -> back-project -> SE3 to the
// other camera -> project -> re-distort, with per-point validity filtering
// (out-of-bounds / invalid depth / behind camera).
#pragma once

#include <vector>

#include "egpt/camera.hpp"

namespace egpt {

struct FeaturePoint {
  int id = -1;
  Vec2 px;        // pixel in source camera (distorted coords)
  bool valid = false;
};

struct TransformResult {
  std::vector<FeaturePoint> points;  // same order as input; valid flag set
  int num_valid = 0;
};

// Project features from cam_src (with a depth map in its frame) into
// cam_dst. ``depth_scale`` converts depth-map units to meters.
TransformResult ProjectFeatures(const std::vector<FeaturePoint>& features,
                                const RadtanCamera& cam_src,
                                const RadtanCamera& cam_dst,
                                const DepthMap& depth_src,
                                double depth_scale = 1.0,
                                double border_margin = 1.0);

}  // namespace egpt
