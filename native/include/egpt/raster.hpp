// Event rasterization + equal-count splitting — the native host hot path.
//
// Same semantics as eventgpt_tpu/ops/raster.py (itself the redesign of the
// reference's per-event Python loop, common/common.py:64-74): white
// background, last event at a pixel wins, polarity 1 -> red, 0 -> blue.
#pragma once

#include <cstdint>
#include <vector>

#include "egpt/events_io.hpp"

namespace egpt {

// out must hold height*width*3 bytes (RGB, row-major).
void RasterizeEvents(const uint16_t* x, const uint16_t* y, const uint8_t* p,
                     size_t n, int height, int width, uint8_t* out);

// Convenience over Event records; auto-sizes to (max_y+1, max_x+1) when
// height/width are 0. Returns frame dims via out params.
std::vector<uint8_t> RasterizeEvents(const std::vector<Event>& events,
                                     int& height, int& width);

// Equal-event-count split points: n slices, slice i = [i*total/n, (i+1)*total/n)
// with the last slice absorbing the remainder (common/common.py:17-37).
std::vector<std::pair<size_t, size_t>> SplitByCount(size_t total, int n);

}  // namespace egpt
