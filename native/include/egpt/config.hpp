// Flat key-value YAML-subset config reader — the ParamHandler equivalent.
//
// The reference's C++ reads a flat "key: v1 v2 ..." YAML via an external
// ParamHandler (EventsDataIO.cpp:46-51, mc_state_estimation_config.yaml).
// This reader covers that format: one "key: values" pair per line, values
// whitespace-separated scalars; '#' comments; later keys override earlier.
#pragma once

#include <map>
#include <optional>
#include <string>
#include <vector>

#include "egpt/camera.hpp"

namespace egpt {

class Config {
 public:
  static std::optional<Config> Load(const std::string& path);
  static Config Parse(const std::string& text);

  bool has(const std::string& key) const { return values_.count(key) > 0; }
  std::optional<std::string> get_str(const std::string& key) const;
  std::optional<double> get_double(const std::string& key) const;
  std::optional<std::vector<double>> get_doubles(const std::string& key) const;

  // Assemble a camera from "<prefix>_intrinsics: fx fy cx cy",
  // "<prefix>_distortion: k1 k2 p1 p2 [k3]", "<prefix>_resolution: w h",
  // "<prefix>_T_base_cam: qx qy qz qw tx ty tz" (quaternion xyzw + xyz, the
  // rig-config convention of mc_state_estimation_config.yaml:1-27).
  std::optional<RadtanCamera> get_camera(const std::string& prefix) const;

 private:
  std::map<std::string, std::string> values_;
};

}  // namespace egpt
