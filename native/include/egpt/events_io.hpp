// Threaded event-stream producer/consumer — the TPU-era EventsDataIO<T>.
//
// Replaces preprocess/feature_track/EventsDataIO.cpp structurally: a producer
// thread reads events from a file (txt "t x y p" lines, or npy structured
// {x,y,t,p} — the same schema dataset/io.py and samples/*.npy use), buffers
// them into ~packet_us packets, and pushes to a mutex-guarded queue; the
// consumer pops all packets up to a time horizon, splitting a straddling
// packet and re-queuing the remainder (PopDataUntil semantics,
// EventsDataIO.cpp:80-145). Live-camera SDK backends (Metavision) are
// replaced by file replay with optional wall-clock pacing
// (GoOfflineTxt's pacing loop, EventsDataIO.cpp:329-335).
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace egpt {

struct Event {
  double t = 0;  // seconds
  uint16_t x = 0, y = 0;
  uint8_t p = 0;
};

struct EventPacket {
  std::vector<Event> events;
  double t_begin = 0, t_end = 0;
};

enum class TimeUnit { kAuto = 0, kSeconds = 1, kMicroseconds = 2 };

class EventsDataIO {
 public:
  struct Options {
    double packet_us = 1000.0;  // ~1 ms packets (EventsDataIO.cpp:386-402)
    bool paced = false;         // replay at wall-clock rate
    double pace_factor = 1.0;   // >1 = faster than real time
    // Txt timestamp unit. kAuto: max value > 1e5 means microseconds —
    // ambiguous for microsecond recordings shorter than 0.1 s, which must
    // set kMicroseconds explicitly.
    TimeUnit time_unit = TimeUnit::kAuto;
  };

  // Two ctors instead of a defaulted Options argument: GCC rejects nested-
  // class NSDMI defaults used as default arguments inside the enclosing class.
  EventsDataIO() = default;
  explicit EventsDataIO(const Options& opts) : opts_(opts) {}
  ~EventsDataIO() { Stop(); }

  // Spawn the producer thread reading a whitespace "t x y p" file
  // (GoOfflineTxt). t in seconds or microseconds (auto-detected: max value
  // > 1e5 means microseconds — no real recording spans 1e5 seconds).
  bool GoOfflineTxt(const std::string& path);

  // Spawn the producer thread reading a structured npy with fields
  // x/y/t/p (the samples/sample1.npy schema; t in microseconds).
  bool GoOfflineNpy(const std::string& path);

  // Push a packet (producer side). Thread-safe.
  void PushData(EventPacket&& packet);

  // Pop every event with t <= horizon (seconds) into out; a packet
  // straddling the horizon is split and its tail re-queued. Returns number
  // of events popped. Non-blocking.
  size_t PopDataUntil(double horizon, std::vector<Event>& out);

  // Offline-mode variant: waits until the producer has pushed packets
  // covering ``horizon`` (or finished the stream) before draining.
  // PopDataUntil alone races the producer thread — an early call can see
  // an empty queue and return 0 events for a window the stream does
  // cover (the feature-track generator's empty-npy flake).
  size_t PopDataUntilBlocking(double horizon, std::vector<Event>& out);

  // True while the producer thread is alive or the queue is non-empty.
  bool Running() const;

  // Stop and join the producer (Stop, EventsDataIO.cpp:28-43).
  void Stop();

  size_t queue_size() const;

 private:
  void ProduceFromVector(std::vector<Event> events);

  Options opts_;
  mutable std::mutex mutex_;
  std::condition_variable cv_;
  std::deque<EventPacket> queue_;
  std::thread producer_;
  std::atomic<bool> producing_{false};
  std::atomic<bool> stop_requested_{false};
};

// Standalone npy loaders (shared with the C-ABI bindings).
// Returns false on parse failure. Handles structured dtypes with x/y/t/p
// fields of unsigned/signed integer or float types, little-endian.
bool LoadEventsNpy(const std::string& path, std::vector<Event>& out);

// Structured-array .npy writer (descr x:<u2, y:<u2, t:<f8, p:<u1) — the
// exact layout LoadEventsNpy and the Python pipeline's
// ops/raster.load_event_npy both read, so the offline feature-track
// generator can emit training windows the JAX data pipeline consumes
// directly (the SURVEY §2.3 seam).
bool SaveEventsNpy(const std::string& path, const std::vector<Event>& events);
bool LoadEventsTxt(const std::string& path, std::vector<Event>& out,
                   TimeUnit unit = TimeUnit::kAuto);

}  // namespace egpt
