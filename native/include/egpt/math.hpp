// Minimal linear-algebra types for the native preprocessing toolchain.
//
// The reference leans on Eigen + Sophus (preprocess/feature_track/CamBase.h:1-9);
// neither ships in this image, and the toolchain needs only 2/3-vectors, 3x3
// matrices and SE3 poses — so they are implemented here, self-contained.
#pragma once

#include <array>
#include <cmath>
#include <cstddef>

namespace egpt {

struct Vec2 {
  double x = 0, y = 0;
  Vec2 operator+(const Vec2& o) const { return {x + o.x, y + o.y}; }
  Vec2 operator-(const Vec2& o) const { return {x - o.x, y - o.y}; }
  Vec2 operator*(double s) const { return {x * s, y * s}; }
  double norm() const { return std::sqrt(x * x + y * y); }
};

struct Vec3 {
  double x = 0, y = 0, z = 0;
  Vec3 operator+(const Vec3& o) const { return {x + o.x, y + o.y, z + o.z}; }
  Vec3 operator-(const Vec3& o) const { return {x - o.x, y - o.y, z - o.z}; }
  Vec3 operator*(double s) const { return {x * s, y * s, z * s}; }
  double dot(const Vec3& o) const { return x * o.x + y * o.y + z * o.z; }
  Vec3 cross(const Vec3& o) const {
    return {y * o.z - z * o.y, z * o.x - x * o.z, x * o.y - y * o.x};
  }
  double norm() const { return std::sqrt(dot(*this)); }
};

struct Mat3 {
  // Row-major.
  std::array<double, 9> m{1, 0, 0, 0, 1, 0, 0, 0, 1};

  static Mat3 identity() { return Mat3{}; }

  double& operator()(int r, int c) { return m[r * 3 + c]; }
  double operator()(int r, int c) const { return m[r * 3 + c]; }

  Vec3 operator*(const Vec3& v) const {
    return {m[0] * v.x + m[1] * v.y + m[2] * v.z,
            m[3] * v.x + m[4] * v.y + m[5] * v.z,
            m[6] * v.x + m[7] * v.y + m[8] * v.z};
  }
  Mat3 operator*(const Mat3& o) const {
    Mat3 r;
    for (int i = 0; i < 3; ++i)
      for (int j = 0; j < 3; ++j) {
        double s = 0;
        for (int k = 0; k < 3; ++k) s += (*this)(i, k) * o(k, j);
        r(i, j) = s;
      }
    return r;
  }
  Mat3 transpose() const {
    Mat3 r;
    for (int i = 0; i < 3; ++i)
      for (int j = 0; j < 3; ++j) r(i, j) = (*this)(j, i);
    return r;
  }
  double det() const {
    return m[0] * (m[4] * m[8] - m[5] * m[7]) - m[1] * (m[3] * m[8] - m[5] * m[6]) +
           m[2] * (m[3] * m[7] - m[4] * m[6]);
  }
  Mat3 inverse() const {
    const double d = det();
    Mat3 r;
    r.m = {(m[4] * m[8] - m[5] * m[7]) / d, (m[2] * m[7] - m[1] * m[8]) / d,
           (m[1] * m[5] - m[2] * m[4]) / d, (m[5] * m[6] - m[3] * m[8]) / d,
           (m[0] * m[8] - m[2] * m[6]) / d, (m[2] * m[3] - m[0] * m[5]) / d,
           (m[3] * m[7] - m[4] * m[6]) / d, (m[1] * m[6] - m[0] * m[7]) / d,
           (m[0] * m[4] - m[1] * m[3]) / d};
    return r;
  }
};

// Unit quaternion (x, y, z, w) + translation — the Sophus::SE3 replacement.
struct SE3 {
  std::array<double, 4> q{0, 0, 0, 1};  // x y z w
  Vec3 t;

  static SE3 identity() { return SE3{}; }

  static SE3 from_quat_trans(double qx, double qy, double qz, double qw, const Vec3& t) {
    SE3 out;
    const double n = std::sqrt(qx * qx + qy * qy + qz * qz + qw * qw);
    out.q = {qx / n, qy / n, qz / n, qw / n};
    out.t = t;
    return out;
  }

  Mat3 rotation() const {
    const double x = q[0], y = q[1], z = q[2], w = q[3];
    Mat3 r;
    r.m = {1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y),
           2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x),
           2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y)};
    return r;
  }

  Vec3 operator*(const Vec3& p) const { return rotation() * p + t; }

  SE3 inverse() const {
    SE3 out;
    out.q = {-q[0], -q[1], -q[2], q[3]};
    out.t = (out.rotation() * t) * -1.0;
    return out;
  }

  SE3 operator*(const SE3& o) const {
    // Hamilton product, then compose translation.
    const double x1 = q[0], y1 = q[1], z1 = q[2], w1 = q[3];
    const double x2 = o.q[0], y2 = o.q[1], z2 = o.q[2], w2 = o.q[3];
    SE3 out;
    out.q = {w1 * x2 + x1 * w2 + y1 * z2 - z1 * y2,
             w1 * y2 - x1 * z2 + y1 * w2 + z1 * x2,
             w1 * z2 + x1 * y2 - y1 * x2 + z1 * w2,
             w1 * w2 - x1 * x2 - y1 * y2 - z1 * z2};
    out.t = rotation() * o.t + t;
    return out;
  }
};

}  // namespace egpt
