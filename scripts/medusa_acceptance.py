"""Trained-Medusa vs lookup drafting on identical live serving traffic.

The round-4 verdict's standing gap: the Medusa machinery existed but no
number showed trained heads accepting more than the suffix-vote lookup
draft. This script is that experiment, fully reproducible in-tree
(VERDICT r4 #2):

  1. Build the deterministic motion-QA corpus
     (``data/motion_corpus.py``): pixels -> direction/speed is learnable,
     per-sample track counts are not echoable.
  2. Finetune the tiny model (full LM + projector — the study needs a
     model that actually *generates* the distribution; LoRA parity is
     stage-2's job, not this experiment's) until its greedy captions
     track the corpus.
  3. Train a Medusa head stack (``train/medusa.py``) on the same data.
  4. Serve the held-out split through three fresh ``ContinuousBatcher``
     instances — lookup draft, trained heads, random heads — with
     identical traffic, budgets and windows, and compare realized
     acceptance (``spec_tokens_per_iteration``: committed tokens per
     model weight pass, the number that buys wall-clock).

Greedy chains must be IDENTICAL across all three (speculation is exact);
only the accept rate may differ. Prints one JSON line.

The reference has no speculation at all (one forward per token,
``/root/reference/model/EventChatModel.py:237-276``) — both columns here
are beyond-parity; the study ranks the framework's own two drafters.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def _finetune(cfg, params, tokenizer, dataset, steps, batch_size, lr, log_every):
    """Full-model finetune (LM + projector; CLIP frozen)."""
    import jax
    import jax.numpy as jnp

    from eventgpt_tpu.train import steps as steps_mod
    from eventgpt_tpu.train.data import batch_iterator
    from eventgpt_tpu.train.optim import linear_warmup_cosine, make_optimizer

    trainable = {"llama": params["llama"], "projector": params["projector"]}
    frozen = {"clip": params["clip"]}

    def combine(trainable, frozen, step=None):
        return {"clip": frozen["clip"], "llama": trainable["llama"],
                "projector": trainable["projector"]}

    opt = make_optimizer(linear_warmup_cosine(lr, steps, max(steps // 20, 1)))
    state = steps_mod.init_train_state(trainable, frozen, opt)
    step_fn = steps_mod.make_train_step(cfg, opt, combine, donate=False)

    step, loss = 0, float("nan")
    epoch = 0
    while step < steps:
        for host in batch_iterator(dataset, batch_size, cfg, shuffle=True,
                                   seed=epoch):
            batch = {k: jnp.asarray(v) for k, v in host.items()}
            state, metrics = step_fn(state, batch)
            step += 1
            if step % log_every == 0 or step == steps:
                loss = float(jax.device_get(metrics["loss"]))
                print(f"[finetune] step {step}/{steps} loss {loss:.4f}",
                      file=sys.stderr, flush=True)
            if step >= steps:
                break
        epoch += 1
    if not loss == loss:
        raise RuntimeError("finetune diverged (NaN)")
    return {"clip": frozen["clip"], "llama": state.trainable["llama"],
            "projector": state.trainable["projector"]}, loss


def _train_heads(cfg, params, dataset, num_heads, steps, batch_size, lr,
                 log_every):
    import jax
    import jax.numpy as jnp
    import optax

    from eventgpt_tpu.train.data import batch_iterator
    from eventgpt_tpu.train.medusa import init_medusa_state, make_medusa_train_step

    opt = optax.adamw(lr)
    state = init_medusa_state(cfg, params, num_heads, opt)
    step_fn = make_medusa_train_step(cfg, opt, donate=False)
    step, loss = 0, float("nan")
    epoch = 0
    while step < steps:
        for host in batch_iterator(dataset, batch_size, cfg, shuffle=True,
                                   seed=1000 + epoch):
            batch = {k: jnp.asarray(v) for k, v in host.items()}
            state, metrics = step_fn(state, batch)
            step += 1
            if step % log_every == 0 or step == steps:
                loss = float(jax.device_get(metrics["loss"]))
                print(f"[medusa] step {step}/{steps} loss {loss:.4f} "
                      f"per_head {[round(float(x), 3) for x in metrics['per_head_loss']]}",
                      file=sys.stderr, flush=True)
            if step >= steps:
                break
        epoch += 1
    if not loss == loss:
        raise RuntimeError("medusa training diverged (NaN)")
    return jax.device_get(state.trainable), loss


def _serve_traffic(params, cfg, traffic, draft_head, speculative, budget,
                   max_batch, eos):
    """One fresh batcher (cold history — the honest serving start), all
    eval requests, -> (answers by submit order, tok/iter, wall_s)."""
    from eventgpt_tpu.serve import ContinuousBatcher

    srv = ContinuousBatcher(
        params, cfg, max_batch=max_batch, max_len=256, chunk=16,
        eos_token_id=eos, speculative=speculative, draft_head=draft_head,
    )
    # Warm every executable, then zero the counters: the first draft
    # config must not pay everyone's compiles, and acceptance counters
    # must reflect only measured traffic.
    srv.warmup(prompt_lens=[len(traffic[0][0]) + 16])
    srv.reset_serving_stats()
    t0 = time.perf_counter()
    rids = [srv.submit(ids, px, budget) for ids, px in traffic]
    outs = srv.run_until_drained()
    wall = time.perf_counter() - t0
    return [outs[r] for r in rids], srv.spec_tokens_per_iteration(), wall


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out_dir", default=None,
                   help="corpus/workspace dir (default: fresh tempdir)")
    p.add_argument("--n_train", type=int, default=96)
    p.add_argument("--n_eval", type=int, default=16)
    p.add_argument("--finetune_steps", type=int, default=600)
    p.add_argument("--medusa_steps", type=int, default=400)
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--medusa_lr", type=float, default=2e-3)
    p.add_argument("--num_heads", type=int, default=3)
    p.add_argument("--speculative", type=int, default=4)
    p.add_argument("--budget", type=int, default=56)
    p.add_argument("--max_batch", type=int, default=1,
                   help="1 = sequential serving, so tokens_per_iteration "
                        "is PER-CHAIN acceptance (comparable to the "
                        "lookup baselines in PERFORMANCE.md); >1 reports "
                        "aggregate per weight pass")
    p.add_argument("--log_every", type=int, default=50)
    p.add_argument("--save_heads", default=None,
                   help="optionally save the trained stack (.npz)")
    args = p.parse_args(argv)

    import jax
    import numpy as np

    from eventgpt_tpu.cli.infer import load_model
    from eventgpt_tpu.constants import EVENT_TOKEN_INDEX
    from eventgpt_tpu.data.motion_corpus import build_motion_corpus

    args_dir = args.out_dir or tempfile.mkdtemp(prefix="medusa_acc_")
    paths = build_motion_corpus(args_dir, args.n_train, args.n_eval)

    cfg, params, tokenizer = load_model("tiny-random", "float32", None, None)

    from eventgpt_tpu.train.data import EventChatDataset

    dataset = EventChatDataset(paths["train"], tokenizer, cfg,
                               event_folder=paths["events"],
                               conv_version="plain")

    t0 = time.perf_counter()
    model, ft_loss = _finetune(cfg, params, tokenizer, dataset,
                               args.finetune_steps, args.batch_size,
                               args.lr, args.log_every)
    ft_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    heads, md_loss = _train_heads(cfg, model, dataset, args.num_heads,
                                  args.medusa_steps, args.batch_size,
                                  args.medusa_lr, args.log_every)
    md_s = time.perf_counter() - t0
    if args.save_heads:
        from eventgpt_tpu.models.medusa import save_medusa

        save_medusa(args.save_heads, heads)

    # Held-out traffic: the serving-side twin of preprocess_plain's layout
    # (bos, event block, newline) — the distribution the model was tuned on.
    with open(paths["eval"]) as f:
        eval_entries = json.load(f)
    from eventgpt_tpu.ops.image import process_event_file

    nl = tokenizer("\n", add_special_tokens=False)["input_ids"]
    bos = getattr(tokenizer, "bos_token_id", None)
    prompt = ([bos] if bos is not None else []) + [EVENT_TOKEN_INDEX] + list(nl)
    traffic = []
    for e in eval_entries:
        _, px = process_event_file(
            os.path.join(paths["events"], e["event"]),
            cfg.num_event_frames, cfg.vision.image_size)
        traffic.append((list(prompt), px))
    eos = getattr(tokenizer, "eos_token_id", None)

    rng = np.random.default_rng(7)
    random_heads = {"w": jax.numpy.asarray(
        rng.normal(size=np.shape(heads["w"])).astype(np.float32) * 0.5)}

    results = {}
    answers = {}
    for name, draft in (("lookup", None), ("medusa_trained", heads),
                        ("medusa_random", random_heads)):
        outs, tpi, wall = _serve_traffic(
            model, cfg, traffic, draft, args.speculative, args.budget,
            args.max_batch, eos)
        results[name] = {"tokens_per_iteration": round(tpi, 3),
                         "wall_s": round(wall, 2)}
        answers[name] = outs

    # Exactness: speculation must never change the greedy chain.
    if not (answers["lookup"] == answers["medusa_trained"]
            == answers["medusa_random"]):
        raise RuntimeError("greedy chains diverged across draft types — "
                           "speculation exactness violated")

    # How well did the model actually learn the distribution? (context for
    # the acceptance numbers; NOT a correctness gate)
    decoded = tokenizer.batch_decode(answers["lookup"],
                                     skip_special_tokens=True)
    exact = sum(
        d.strip() == e["conversations"][1]["value"].strip()
        for d, e in zip(decoded, eval_entries))

    record = {
        "metric": "medusa_vs_lookup_tokens_per_iteration",
        "value": results["medusa_trained"]["tokens_per_iteration"],
        "unit": "tok/weight-pass",
        "lookup": results["lookup"],
        "medusa_trained": results["medusa_trained"],
        "medusa_random": results["medusa_random"],
        "speculative_window": args.speculative,
        "num_heads": args.num_heads,
        "traffic_requests": len(traffic),
        "budget": args.budget,
        "finetune": {"steps": args.finetune_steps, "loss": round(ft_loss, 4),
                     "wall_s": round(ft_s, 1)},
        "medusa_train": {"steps": args.medusa_steps,
                         "loss": round(md_loss, 4),
                         "wall_s": round(md_s, 1)},
        "eval_caption_exact": f"{exact}/{len(decoded)}",
        "workspace": args_dir,
    }
    print(json.dumps(record))
    return record


if __name__ == "__main__":
    main()
