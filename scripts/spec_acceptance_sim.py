"""Grounded speculative-decoding acceptance estimate from REAL outputs.

The bench bracket (PERFORMANCE.md) bounds speculative throughput between a
zero-acceptance floor and a fully-draftable ceiling, but where a real
checkpoint lands depends only on the TOKEN STREAM it emits — acceptance is
a pure function of the generated text, not of the weights. The reference
publishes its actual answers for its samples (``/root/reference/README.md:
92-160``); this tool replays the EXACT drafting rule of
``models/eventchat._suffix_vote_drafts`` (longest-suffix majority-vote
lookup, re-queried per draft position, optional server-wide history
buffer, window W, first-mismatch correction) over prompt+answer and counts
committed tokens per verification iteration. ``--draft bigram`` replays
round 3's latest-earlier-bigram rule for comparison.

No LLaMA sentencepiece model ships in this image, so two tokenizations
bracket the real one: WORD-level splits (conservative — subword tokenizers
add deterministic within-word continuations that only raise acceptance)
and BYTE-level (optimistic — character n-grams repeat far more often).
Projected tok/s = tokens/iteration x the measured zero-acceptance rate
(``floor_tok_s`` = iterations/second, shape-static per window).

Usage: python scripts/spec_acceptance_sim.py [--windows 4,8,16]
       [--draft suffix|bigram] [--history 2048|0]
"""

from __future__ import annotations

import argparse
import json
import re
from collections import Counter

# Conversations transcribed from /root/reference/README.md:92-160 — the
# reference's published sample outputs, its only correctness artifact.
# Grouped by conversation: the README shows Q1/Q2/Q3 as TURNS of one chat,
# and at serve time prior turns sit in the prompt, so they are lookup
# context (later answers echo earlier ones heavily — that is exactly what
# prompt-lookup drafting exploits).
CONVERSATIONS = [
    [("Describe in detail what happened in the scene.",
      "The scene depicts a person holding a large fish in a body of water. "
      "The individual is wearing a cap and a jacket, and the fish has a long, "
      "slender body with a prominent dorsal fin and tail. The background shows "
      "a natural environment with trees and grassy areas."),
     ("What is the person holding in their hands?",
      "The person is holding a large fish in their hands."),
     ("Where is the person in the image?",
      "The person in the scene is standing near a body of water, holding a "
      "large fish.")],
    [("What activities are occurring in this scene?",
      "The scene depicts a pedestrian walking on the sidewalk, carrying "
      "shopping bags. A cyclist is riding on the right side of the street, "
      "and a car is stationary or moving slowly in the middle of the street. "
      "The overall activity suggests a typical urban street environment."),
     ("What mode of transportation is being used by one of the individuals?",
      "The individual is using a bicycle as their mode of transportation.")],
    [("Describe in detail what happened in the scene.",
      "The scene depicts a dropper releasing a single liquid drop against a "
      "dark background. The droplet forms and drops downward, leaving a faint "
      "trail behind it."),
     ("What is the dropper releasing?",
      "The dropper is releasing a single liquid drop."),
     ("Would the droplet remain suspended in the air after falling?",
      "Yes, the droplet would remain suspended in the air after falling.")],
    [("Describe in detail what happened in the scene.",
      "The scene depicts a die spinning rapidly in a precise clockwise "
      "direction while balanced on one of its corners. The angular momentum "
      "of the die is maintained through persistent angular momentum transfer, "
      "allowing it to maintain this unusual spinning position."),
     ("In which direction is the die rotating?",
      "The die is rotating rapidly in a precise clockwise direction, creating "
      "visible rotational momentum as it whirls around its axis."),
     ("How is the die rotating?",
      "The die is rotating rapidly in a precise clockwise direction, creating "
      "a visible blurred circular pattern around its center.")],
]

# The Vicuna-v1 system prompt every EventGPT conversation starts with
# (data/conversation.py, dataset/conversation.py:212-222) — part of the
# lookup context at serve time, so part of the simulation context.
SYSTEM = ("A chat between a curious user and an artificial intelligence "
          "assistant. The assistant gives helpful, detailed, and polite "
          "answers to the user's questions.")

LOOKUP_MAX = 8  # mirrors models/eventchat.SPEC_LOOKUP_MAX


def tokenize(text: str, mode: str):
    if mode == "word":
        return re.findall(r"\w+|[^\w\s]", text)
    return list(text.encode())


def _draft_suffix_vote(base, suffix, hist):
    """One draft token by the device rule (_suffix_vote_drafts): score
    every committed position of ``base`` (ends j <= len(base)-2, so the
    continuation is committed too) and of ``hist`` by trailing-match depth
    against ``suffix`` (newest first, up to LOOKUP_MAX); among positions
    at the global max depth, majority-vote their continuations (tie ->
    smallest token, argmax order); no match -> repeat the newest token."""
    best_l = 0
    votes = Counter()
    for toks in (base, hist):
        for j in range(0, len(toks) - 1):
            l = 0
            while (l < LOOKUP_MAX and j - l >= 0 and l < len(suffix)
                   and suffix[l] == toks[j - l]):
                l += 1
            if l == 0:
                continue
            if l > best_l:
                best_l = l
                votes = Counter()
            if l == best_l:
                votes[toks[j + 1]] += 1
    if best_l == 0 or not votes:
        return suffix[0] if suffix else None
    top = max(votes.values())
    return min(t for t, c in votes.items() if c == top)


def simulate_suffix(context, answer, window: int, hist):
    """Replay _suffix_vote_drafts + greedy verification over a forced
    chain. Token 1 comes from prefill (no iteration); each iteration
    drafts window-1 tokens (re-querying as drafted tokens extend the
    suffix), commits accepted-drafts + 1 correction — exactly the device
    loop."""
    buf = list(context) + [answer[0]]
    n_gen, iters = 1, 0
    n = len(answer)
    while n_gen < n:
        iters += 1
        suffix = list(reversed(buf[-LOOKUP_MAX:]))
        # Match ends j <= len(buf)-2 (the device's committed-continuation
        # rule: _draft_suffix_vote itself stops at len(toks)-2).
        base = buf
        accepted = 0
        for _ in range(window - 1):
            d = _draft_suffix_vote(base, suffix, hist)
            if n_gen + accepted >= n - 1:
                break
            if d == answer[n_gen + accepted]:
                accepted += 1
                suffix = [d] + suffix[:LOOKUP_MAX - 1]
            else:
                break
        commit = min(accepted + 1, n - n_gen)
        buf.extend(answer[n_gen:n_gen + commit])
        n_gen += commit
    return n_gen, iters


def simulate_bigram(context, answer, window: int, hist=None):
    """Round 3's rule (latest earlier bigram, block continuation) — kept
    for comparison via --draft bigram."""
    buf = list(context) + [answer[0]]
    n_gen, iters = 1, 0
    n = len(answer)
    while n_gen < n:
        iters += 1
        a, c0 = buf[-2], buf[-1]
        j_star = -1
        for j in range(len(buf) - 2, 0, -1):
            if buf[j] == c0 and buf[j - 1] == a:
                j_star = j
                break
        accepted = 0
        for i in range(1, window):
            if n_gen + accepted >= n - 1:
                break
            draft = (buf[j_star + i]
                     if (j_star >= 0 and j_star + i < len(buf)) else c0)
            if draft == answer[n_gen + accepted]:
                accepted += 1
            else:
                break
        commit = min(accepted + 1, n - n_gen)
        buf.extend(answer[n_gen:n_gen + commit])
        n_gen += commit
    return n_gen, iters


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--windows", default="4,8,16")
    p.add_argument("--draft", default="suffix", choices=["suffix", "bigram"])
    p.add_argument("--history", type=int, default=2048,
                   help="server history buffer length in tokens "
                        "(serve.py history_len; 0 disables)")
    p.add_argument("--floor_tok_s", type=float, default=71.07,
                   help="measured iterations/second at window 8 "
                        "(BENCH spec_floor_tok_s; scales only mildly with W)")
    args = p.parse_args()

    for mode in ("word", "byte"):
        for w in [int(x) for x in args.windows.split(",")]:
            for multiturn in (False, True):
                tot_tok = tot_it = 0
                history: list = []
                for conv in CONVERSATIONS:
                    ctx = tokenize(SYSTEM, mode)
                    for q, ans in conv:
                        turn_ctx = ctx + tokenize(
                            " USER: " + q + " ASSISTANT: ", mode)
                        a_t = tokenize(ans, mode)
                        if args.draft == "suffix":
                            t, i = simulate_suffix(turn_ctx, a_t, w, history)
                        else:
                            t, i = simulate_bigram(turn_ctx, a_t, w)
                        tot_tok += t
                        tot_it += i
                        if multiturn:  # prior turns stay in the prompt
                            ctx = turn_ctx + a_t
                        if args.history:
                            history = (history + tokenize(" " + q, mode)
                                       + a_t)[-args.history:]
                tpi = tot_tok / max(tot_it, 1)
                print(json.dumps({
                    "tokenization": mode, "window": w, "draft": args.draft,
                    "history": args.history if args.draft == "suffix" else 0,
                    "context": "multiturn" if multiturn else "single",
                    "tokens": tot_tok, "iterations": tot_it,
                    "tokens_per_iteration": round(tpi, 2),
                    "projected_tok_s_7b": round(tpi * args.floor_tok_s, 1),
                }))


if __name__ == "__main__":
    main()
