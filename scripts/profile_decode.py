#!/usr/bin/env python3
"""Capture a jax.profiler trace of the decode loop and print an op-time
breakdown — the tool behind PERFORMANCE.md's decomposition.

Runs the product decode path (flash prefill + whole-budget while_loop) at a
chosen preset/quantization, traces one timed loop invocation, then parses the
chrome-trace export to attribute device time to fusions. On a v5e this is
how the KV-cache-restacking copies (~2 ms/token) and the per-dispatch tunnel
overhead were isolated.

Usage:
  python scripts/profile_decode.py [--preset 7b|13b|tiny] [--quant int8|int4|bf16]
      [--decode_tokens 64] [--trace_dir /tmp/egpt-trace] [--top 20]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(args) -> str:
    """Run + trace one decode-loop invocation; stamps a meta.json next to
    the trace so later --summarize_only runs divide by the right budget."""
    import jax
    import jax.numpy as jnp

    from bench import _build_params, _event_pixels, _sync
    from eventgpt_tpu.config import EventChatConfig
    from eventgpt_tpu.data.tokenizer import split_at_event
    from eventgpt_tpu.models import eventchat, llama as llama_mod
    from eventgpt_tpu.models.eventchat import (
        _decode_loop_jit, _pad_batch, _prefill_jit, splice_embeddings,
    )

    cfg = {"7b": EventChatConfig.eventgpt_7b,
           "13b": EventChatConfig.eventgpt_13b,
           "tiny": EventChatConfig.tiny}[args.preset]()
    dtype = jnp.bfloat16
    quant = args.quant if args.preset in ("7b", "13b") else "bf16"
    if quant != args.quant:
        print(f"[profile] preset {args.preset} forces quant={quant} "
              f"(requested {args.quant})", file=sys.stderr)
    print(f"[profile] preset={args.preset} quant={quant} "
          f"decode_tokens={args.decode_tokens}", file=sys.stderr)
    params = _build_params(cfg, dtype, quant)
    pixels = jnp.asarray(_event_pixels(cfg, 1), dtype)
    ev = eventchat.encode_events_batch(params, cfg, pixels)
    _sync(ev)

    ids = [1] + [7] * 34 + [-200] + [9] * 16
    embeds = [splice_embeddings(params, cfg, split_at_event(ids), ev[0])]
    padded, mask, _ = _pad_batch(embeds)
    prompt_len = 35 + cfg.num_event_tokens + 16
    cache_len = ((prompt_len + args.decode_tokens + 64) // 64) * 64

    def prefill_once():
        cache = llama_mod.init_kv_cache(cfg.llama, 1, cache_len, dtype)
        return _prefill_jit(params, cfg, padded, mask, cache, True)

    key = jax.random.PRNGKey(0)

    def loop(lg, cch):
        toks, n, cch = _decode_loop_jit(
            params, cfg, lg, cch, key, args.decode_tokens, 0.0, 1.0, -1
        )
        del cch  # returned only for donation aliasing
        return toks, n

    last, cache = prefill_once()
    _sync(last)
    toks, _ = loop(last, cache)  # compile
    _sync(toks)
    last, cache = prefill_once()
    _sync(last)
    with jax.profiler.trace(args.trace_dir):
        toks, _ = loop(last, cache)
        _sync(toks)
    with open(os.path.join(args.trace_dir, "meta.json"), "w") as f:
        json.dump({"decode_tokens": args.decode_tokens,
                   "preset": args.preset, "quant": quant}, f)
    return args.trace_dir


def summarize(trace_dir: str, decode_tokens: int, top: int) -> None:
    meta_path = os.path.join(trace_dir, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        if meta.get("decode_tokens") != decode_tokens:
            print(f"[profile] trace was captured with decode_tokens="
                  f"{meta.get('decode_tokens')}; using that for the "
                  f"per-token math", file=sys.stderr)
            decode_tokens = int(meta["decode_tokens"])
    paths = glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz"))
    if not paths:
        sys.exit(f"no chrome trace found under {trace_dir}")
    with gzip.open(sorted(paths)[-1], "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    pids = {e["pid"]: e["args"].get("name", "")
            for e in events if e.get("ph") == "M" and e.get("name") == "process_name"}
    dev_pids = {p for p, n in pids.items() if "TPU" in n or "/device" in n.lower()}
    tot, cnt = collections.Counter(), collections.Counter()
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in dev_pids:
            tot[e.get("name", "?")] += e.get("dur", 0)
            cnt[e.get("name", "?")] += 1
    # The whole-loop spans double-count their children; report them first,
    # then per-op rows.
    loops = [(n, d) for n, d in tot.items() if n.startswith(("jit_", "while"))]
    for name, dur in sorted(loops, key=lambda x: -x[1]):
        print(f"{dur / 1e3:9.2f} ms  total   {name[:80]}")
    if loops:
        per_tok = max(d for _, d in loops) / 1e3 / decode_tokens
        print(f"-> device-side {per_tok:.2f} ms/token "
              f"({1e3 / per_tok:.1f} tok/s before dispatch overhead)")
    print(f"{'ms':>9}  {'count':>6}  op")
    shown = 0
    for name, dur in tot.most_common():
        if name.startswith(("jit_", "while")):
            continue
        print(f"{dur / 1e3:9.2f}  {cnt[name]:6d}  {name[:80]}")
        shown += 1
        if shown >= top:
            break


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="7b", choices=["7b", "13b", "tiny"])
    p.add_argument("--quant", default="int8", choices=["int8", "int4", "bf16"])
    p.add_argument("--decode_tokens", type=int, default=64)
    p.add_argument("--trace_dir", default="/tmp/egpt-trace")
    p.add_argument("--top", type=int, default=20)
    p.add_argument("--summarize_only", action="store_true",
                   help="skip capture; parse an existing --trace_dir")
    args = p.parse_args()
    if not args.summarize_only:
        capture(args)
    summarize(args.trace_dir, args.decode_tokens, args.top)


if __name__ == "__main__":
    main()
