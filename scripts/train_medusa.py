"""Train a Medusa draft-head stack on a finetune dataset.

The product loop for the trained-draft serving story: take a (finetuned)
EventChat checkpoint + the same dataset JSON the stage-2 trainer eats,
freeze the whole model, fit only the (K, D, D) head stack
(``train/medusa.py``), and save an ``.npz`` that ``--draft_head`` on the
infer CLI / the batcher / the HTTP server loads. Heads learn
P(token_{t+k+2} | hidden_t) over the model's own supervision targets —
a few hundred steps at 7B is the Medusa paper's regime.

Smoke (tiny random weights, toy data):
  python scripts/train_medusa.py --model_path tiny-random \
      --data_path qa.json --event_folder data/ --num_heads 3 \
      --max_steps 20 --out medusa.npz
"""

from __future__ import annotations

import argparse
import json
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model_path", default="tiny-random")
    p.add_argument("--tokenizer_path", default=None)
    p.add_argument("--data_path", required=True)
    p.add_argument("--event_folder", default="")
    p.add_argument("--conv_version", default="v1")
    p.add_argument("--num_heads", type=int, default=3,
                   help="draft heads K (serve with speculative <= K+1)")
    p.add_argument("--max_steps", type=int, default=200)
    p.add_argument("--batch_size", type=int, default=2)
    p.add_argument("--learning_rate", type=float, default=1e-3)
    p.add_argument("--max_len", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--logging_steps", type=int, default=10)
    p.add_argument("--dtype", default="float32",
                   choices=["bfloat16", "float32"])
    p.add_argument("--out", default="medusa.npz")
    # prepare_model (shared with the infer/eval CLIs) reads these:
    p.add_argument("--use_event_qformer", action="store_true")
    p.add_argument("--pretrain_query_embedder", default=None)
    p.add_argument("--pretrain_attention_layers", default=None)
    p.add_argument("--spatial_temporal_encoder", default=True,
                   type=lambda s: s.lower() not in ("false", "0"))
    p.add_argument("--quant", default="none",
                   choices=["none", "int8", "int4"],
                   help="frozen-base storage during head training")
    p.add_argument("--fuse_params", action="store_true")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from eventgpt_tpu.cli.infer import load_model, prepare_model
    from eventgpt_tpu.train.data import EventChatDataset, batch_iterator
    from eventgpt_tpu.train.medusa import (
        init_medusa_state, make_medusa_train_step, save_medusa,
    )
    from eventgpt_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    cfg, params, tokenizer = load_model(
        args.model_path, args.dtype, None, args.tokenizer_path
    )
    cfg, params = prepare_model(cfg, params, tokenizer, args)

    dataset = EventChatDataset(
        args.data_path, tokenizer, cfg, event_folder=args.event_folder,
        conv_version=args.conv_version,
    )
    opt = optax.adamw(args.learning_rate)
    state = init_medusa_state(cfg, params, args.num_heads, opt)
    step_fn = make_medusa_train_step(cfg, opt)

    step = 0
    t0 = time.perf_counter()
    last = {"loss": float("nan")}
    while step < args.max_steps:
        for host in batch_iterator(
            dataset, args.batch_size, cfg, shuffle=True,
            seed=args.seed + step, max_len=args.max_len,
        ):
            batch = {k: jnp.asarray(v) for k, v in host.items()}
            state, metrics = step_fn(state, batch)
            step += 1
            if step % args.logging_steps == 0 or step == args.max_steps:
                last = {
                    "step": step,
                    "loss": float(metrics["loss"]),
                    "per_head": [round(float(x), 4)
                                 for x in metrics["per_head_loss"]],
                    "grad_norm": float(metrics["grad_norm"]),
                    "s_per_step": round(
                        (time.perf_counter() - t0) / step, 3),
                }
                print(json.dumps(last))
            if step >= args.max_steps:
                break
    if not np.isfinite(last["loss"]):
        raise RuntimeError(f"medusa training diverged: loss={last['loss']}")
    save_medusa(args.out, jax.device_get(state.trainable))
    print(f"saved {args.num_heads}-head stack to {args.out}")
    return last


if __name__ == "__main__":
    main()
