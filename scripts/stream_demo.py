#!/usr/bin/env python3
"""Streaming event-QA demo: native threaded IO -> windowed model answers.

Connects the two halves the reference ships separately and never joins: the
C++ threaded event-stream consumer (EventsDataIO's PushData/PopDataUntil
seam, via the ctypes bridge) feeds 50 ms windows into the rasterize ->
CLIP -> projector -> LLM pipeline, answering the query once per window —
the "understanding of high-speed scenes within 50 ms" scenario the
reference README describes (README.md:119) as an actual running loop.

Usage:
  python scripts/stream_demo.py [--events stream.txt|structured.npy]
      [--model_path tiny-random] [--query "..."] [--window_ms 50]
      [--max_windows 3] [--paced] [--pace_factor 10]

Without --events, a structured npy is synthesized from the reference's
sample1.npy (whose on-disk form is a pickled dict the native reader
deliberately does not parse).

Threading note (audited by ``scripts/egpt_check.py``, ISSUE 8): the
only concurrency here lives INSIDE the native reader (its own C++
consumer thread behind the ctypes seam); the Python side runs the
rasterize -> CLIP -> LLM pipeline on the main thread with no shared
mutable Python state — nothing for the lock-discipline rule to guard,
and the scan keeps it that way.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SAMPLE = "/root/reference/samples/sample1.npy"


def synthesize_stream(tmp_dir: str) -> str:
    """Reference sample (pickled dict) -> structured npy the native
    streaming reader consumes (shared layout helper in ops/raster)."""
    from eventgpt_tpu.ops.raster import events_to_structured_stream, load_event_npy

    path = os.path.join(tmp_dir, "stream_demo_events.npy")
    np.save(path, events_to_structured_stream(load_event_npy(SAMPLE)))
    return path


def main(argv=None):
    from eventgpt_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    p = argparse.ArgumentParser(description="Streaming event-QA demo")
    p.add_argument("--events", type=str, default=None,
                   help="txt ('t x y p') or structured npy stream")
    p.add_argument("--model_path", type=str, default="tiny-random")
    p.add_argument("--tokenizer_path", type=str, default=None)
    p.add_argument("--query", type=str, default="What is happening?")
    p.add_argument("--conv_mode", type=str, default="eventgpt_v1")
    p.add_argument("--window_ms", type=float, default=50.0)
    p.add_argument("--max_windows", type=int, default=3)
    p.add_argument("--max_new_tokens", type=int, default=32)
    p.add_argument("--paced", action="store_true",
                   help="replay at wall-clock rate")
    p.add_argument("--pace_factor", type=float, default=1.0)
    # prepare_model surface (parity with cli/infer.py).
    p.add_argument("--dtype", default="bfloat16", choices=["bfloat16", "float32"])
    p.add_argument("--quant", default="none", choices=["none", "int8", "int4"])
    p.add_argument("--speculative", type=int, default=0,
                   help="speculative greedy decode window (exact-equivalent; "
                        "cuts per-answer decode latency when text repeats)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--use_event_qformer", action="store_true")
    p.add_argument("--pretrain_query_embedder", type=str, default=None)
    p.add_argument("--pretrain_attention_layers", type=str, default=None)
    args = p.parse_args(argv)

    from eventgpt_tpu.cli.infer import load_model, prepare_model
    from eventgpt_tpu.data.conversation import prepare_event_prompt
    from eventgpt_tpu.data.tokenizer import tokenize_with_event
    from eventgpt_tpu.models import eventchat
    from eventgpt_tpu.native import EventStream, available
    from eventgpt_tpu.ops.image import clip_preprocess_batch
    from eventgpt_tpu.ops.raster import events_to_frames, events_window_us

    if not available():
        sys.exit("libegpt_native.so not built; run scripts/build_native.sh")

    events_path = args.events
    if events_path is None:
        if not os.path.exists(SAMPLE):
            sys.exit("no --events given and the reference sample is absent")
        import tempfile

        events_path = synthesize_stream(tempfile.gettempdir())
        print(f"[stream] synthesized {events_path} from sample1.npy",
              file=sys.stderr)

    cfg, params, tokenizer = load_model(
        args.model_path, args.dtype, None, args.tokenizer_path
    )
    cfg, params = prepare_model(cfg, params, tokenizer, args)
    input_ids = tokenize_with_event(
        prepare_event_prompt(args.query, args.conv_mode), tokenizer
    )

    window_s = args.window_ms / 1e3
    answered = 0
    # One consolidated array per field; events behind the cursor are dropped
    # after each emission round so memory and per-window work stay bounded
    # by the window population, not the whole recording.
    buf = {k: np.empty(0, d) for k, d in
           (("x", np.uint16), ("y", np.uint16), ("t", np.float64), ("p", np.uint8))}
    cursor = None

    with EventStream(events_path, paced=args.paced,
                     pace_factor=args.pace_factor) as stream:
        while answered < args.max_windows:
            out = stream.pop_until(1e18)  # drain whatever the producer has
            if out["t"].size:
                buf = {k: np.concatenate([buf[k], out[k]]) for k in buf}
            t_all = buf["t"]
            if cursor is None and t_all.size:
                cursor = float(t_all.min())
            # Emit every complete window currently in the buffer.
            while (cursor is not None and t_all.size
                   and (t_all.max() >= cursor + window_s
                        or not stream.running())
                   and answered < args.max_windows):
                sel = (t_all >= cursor) & (t_all < cursor + window_s)
                if sel.sum() >= cfg.num_event_frames:
                    ev = events_window_us(buf, sel)
                    t0 = time.perf_counter()
                    frames = events_to_frames(ev, cfg.num_event_frames)
                    pixels = clip_preprocess_batch(frames, cfg.vision.image_size)
                    out_ids = eventchat.generate(
                        params, cfg, [input_ids], pixels[None],
                        max_new_tokens=args.max_new_tokens, temperature=0.0,
                        eos_token_id=getattr(tokenizer, "eos_token_id", None),
                        speculative=args.speculative,
                    )[0]
                    answer = tokenizer.batch_decode(
                        [out_ids], skip_special_tokens=True
                    )[0].strip()
                    dt = time.perf_counter() - t0
                    print(f"[{cursor * 1e3:8.1f}ms +{args.window_ms:.0f}ms | "
                          f"{int(sel.sum())} events | {dt * 1e3:.0f} ms] "
                          f"{answer}")
                    answered += 1
                cursor += window_s
                if not stream.running() and t_all.max() < cursor:
                    break
            if cursor is not None and t_all.size:
                keep = t_all >= cursor  # windows only advance
                if not keep.all():
                    buf = {k: buf[k][keep] for k in buf}
                    t_all = buf["t"]
            if not stream.running() and (t_all.size == 0
                                         or (cursor is not None
                                             and t_all.max() < cursor)):
                break
            time.sleep(0.005)
    print(f"[stream] answered {answered} window(s)", file=sys.stderr)
    return answered


if __name__ == "__main__":
    main()
