#!/usr/bin/env python
"""Static telemetry lint — now a thin shim (ISSUE 8 satellite).

The five rules born here (hot-path clocks, metric-name grammar +
register-once, catalogue coverage, fault-site test coverage, bounded
label cardinality) moved into the unified static-analysis framework as
``eventgpt_tpu/analysis/telemetry_rules.py`` and run, alongside the
lock-discipline / host-sync / jit-hygiene analyzers, via
``scripts/egpt_check.py``. This shim keeps the legacy entry point and
the ``run_lint(root) -> List[str]`` surface byte-compatible so
``tests/test_lint_telemetry.py`` (and any operator muscle memory) keeps
working: same violation strings, same exit semantics.

Rule catalogue, annotation and waiver grammar: OBSERVABILITY.md
"Static analysis".
"""

from __future__ import annotations

import os
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Import the package (not just telemetry_rules) so every rule id is
# registered before waiver validation runs — a lock/hot-sync waiver in
# the tree must not read as "unknown rule" to a telemetry-only pass.
from eventgpt_tpu.analysis import TELEMETRY_RULES
from eventgpt_tpu.analysis.core import load_sources, run_checks


def run_lint(root: str) -> List[str]:
    """Returns the violation list (empty = clean) — the legacy string
    form (``file:line: message``). Waivers apply as everywhere in the
    framework; only unwaived findings are violations."""
    findings = run_checks(root, TELEMETRY_RULES,
                          sources=load_sources(root))
    out: List[str] = []
    for f in findings:
        if f.waived:
            continue
        if f.rule == "waiver":
            # The legacy surface predates waivers: report malformed
            # waiver comments too (a silent suppression is worse).
            out.append(f"{f.file}:{f.line}: {f.message}")
        elif not f.file:
            out.append(f.message)
        elif not f.line:
            out.append(f"{f.file}: {f.message}")
        else:
            out.append(f"{f.file}:{f.line}: {f.message}")
    return out


def main() -> int:
    root = sys.argv[1] if len(sys.argv) > 1 else _REPO
    violations = run_lint(root)
    for v in violations:
        print(v)
    print(f"lint_telemetry: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
