#!/usr/bin/env python
"""Static telemetry lint (ISSUE 3 satellite; the fast tier runs it via
``tests/test_lint_telemetry.py``, or run it directly: prints violations
and exits non-zero when any exist).

Rule 1 — hot paths use ``time.perf_counter``, never ``time.time``:
wall-clock jumps (NTP slews, suspend/resume) would corrupt latency
histograms, deadlines and the pipelined-overlap accounting. Hot paths
are the serving scheduler, the obs package itself, the fault probes, the
jitted-step helpers, prefetch, and the kernels. Deliberate wall-clock
users stay OFF this list: ``train/resilience.py`` stamps heartbeat files
with epoch time for EXTERNAL watchdogs, and ``cli/serve.py``'s uptime is
human-facing.

Rule 2 — metric registration: every ``.counter(``/``.gauge(``/
``.histogram(`` call with a string-literal name uses a name matching
``egpt_[a-z0-9_]+``, and each name is registered exactly once across the
runtime tree (the obs/metrics.py central-catalogue rule: call sites
import metric objects, they never register). Tests are excluded — they
build private registries with throwaway names.

Rule 3 — catalogue coverage (ISSUE 4 satellite): every registered
``egpt_*`` metric has a row in OBSERVABILITY.md (literal name mention).
An operator hunting a dashboard number must find its meaning in the
catalogue; a metric that ships undocumented "passes" silently forever.

Rule 4 — fault-site test coverage (ISSUE 5 satellite): every
``faults.maybe_fail``/``maybe_delay`` site name wired in the runtime
tree (``eventgpt_tpu/``) appears, by literal name, in at least one
chaos/faults test — a tests/ file that actually arms injection
(``faults.configure(`` or ``EGPT_FAULTS``). A fault site nobody can
reach from a test is exactly the dead handling code ``faults.py``
exists to prevent.

Rule 5 — bounded label cardinality (ISSUE 6 satellite): every labelled
metric observation (``.inc(k=v)`` / ``.observe(x, k=v)`` /
``.set(x, k=v)`` on a catalogued metric object) draws its label values
from the fixed enum declared in the catalogue
(``obs/metrics.py::METRIC_LABELS`` — a pure literal this lint reads
with ``ast.literal_eval``). Violations: a label key with no declared
enum, a literal value outside the enum, a computed value (f-string /
str()/format — the unbounded shapes), a numeric literal, or a
request-id-shaped label key (``rid``/``id``/...). Additionally every
fault site found by rule 4's scan must be a member of
``egpt_fault_trips_total``'s ``site`` enum, so a new site cannot ship
without extending it. The metric classes re-enforce the enums at
observe time; this rule catches the violation before anything runs.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List

HOT_PATHS = (
    "eventgpt_tpu/serve.py",
    "eventgpt_tpu/faults.py",
    "eventgpt_tpu/obs/",
    "eventgpt_tpu/train/steps.py",
    "eventgpt_tpu/train/prefetch.py",
    "eventgpt_tpu/ops/",
)
# Trees scanned for metric registrations (rule 2). tests/ is excluded on
# purpose: private test registries use throwaway names.
METRIC_SCAN = ("eventgpt_tpu", "scripts", "bench.py")

METRIC_NAME_RE = re.compile(r"^egpt_[a-z0-9_]+$")
_REG_RE = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*['\"]([A-Za-z0-9_.:-]+)['\"]")
# Rule 4: fault-probe call sites in the runtime tree (string-literal
# site names only — the grammar faults.py documents).
_FAULT_SITE_RE = re.compile(
    r"maybe_(?:fail|delay)\(\s*['\"]([A-Za-z0-9_.]+)['\"]")
# A tests/ file counts as a chaos/faults test iff it arms injection.
_FAULT_TEST_RE = re.compile(r"faults\.configure\(|EGPT_FAULTS")
# Rule 5: metric observation methods (labels arrive as kwargs) and the
# non-label kwargs they accept; label keys that smell like per-request
# identity are banned outright, whatever their values.
_OBS_METHODS = ("inc", "observe", "set")
_NON_LABEL_KWARGS = ("n",)
_BANNED_LABEL_KEYS = ("rid", "request_id", "req_id", "id", "uid",
                      "user", "user_id", "session_id")


def _is_hot(rel: str) -> bool:
    return any(rel == h or (h.endswith("/") and rel.startswith(h))
               for h in HOT_PATHS)


def _py_files(root: str) -> List[str]:
    out = []
    for scan in METRIC_SCAN:
        p = os.path.join(root, scan)
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, _, files in os.walk(p):
            out.extend(os.path.join(dirpath, f) for f in sorted(files)
                       if f.endswith(".py"))
    return sorted(out)


def _check_time_time(rel: str, tree: ast.AST, out: List[str]) -> None:
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "time"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "time"):
            out.append(f"{rel}:{node.lineno}: time.time() in a hot path "
                       f"(use time.perf_counter)")
        if (isinstance(node, ast.ImportFrom) and node.module == "time"
                and any(a.name == "time" for a in node.names)):
            out.append(f"{rel}:{node.lineno}: 'from time import time' in "
                       f"a hot path (use time.perf_counter)")


def run_lint(root: str) -> List[str]:
    """Returns the violation list (empty = clean)."""
    violations: List[str] = []
    seen: Dict[str, str] = {}  # metric name -> first registration site
    parsed: List[tuple] = []   # (rel, src, tree) for the AST passes
    for path in _py_files(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path) as f:
            src = f.read()
        try:
            tree = ast.parse(src, rel)
        except SyntaxError as e:
            violations.append(f"{rel}: unparseable ({e})")
            continue
        parsed.append((rel, src, tree))
        if _is_hot(rel):
            _check_time_time(rel, tree, violations)
        for m in _REG_RE.finditer(src):
            # \s crosses newlines: registrations wrap the name to the
            # line after the call in the catalogue's house style.
            name = m.group(1)
            site = f"{rel}:{src.count(chr(10), 0, m.start()) + 1}"
            if not METRIC_NAME_RE.match(name):
                violations.append(
                    f"{site}: metric name {name!r} does not match "
                    f"{METRIC_NAME_RE.pattern}")
            if name in seen:
                violations.append(
                    f"{site}: metric {name!r} registered twice "
                    f"(first at {seen[name]}) — define metrics once, "
                    f"in obs/metrics.py")
            else:
                seen[name] = site
    if not seen:
        violations.append("no metric registrations found — the scan "
                          "pattern or tree layout changed under the lint")
    _check_catalogue(root, seen, violations)
    fault_sites = _check_fault_coverage(root, violations)
    _check_label_enums(parsed, fault_sites, violations)
    return violations


def _metric_var_map(parsed: List[tuple]) -> Dict[str, str]:
    """Assignment targets bound to a metric registration, anywhere in
    the scanned tree — how rule 5 resolves an observation's receiver
    (``SERVE_TTFT.observe`` / ``obs_metrics.SERVE_TTFT.observe``) back
    to its catalogue entry."""
    out: Dict[str, str] = {}
    for _rel, _src, tree in parsed:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr in ("counter", "gauge",
                                                 "histogram")
                    and node.value.args
                    and isinstance(node.value.args[0], ast.Constant)
                    and isinstance(node.value.args[0].value, str)):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value.args[0].value
    return out


def _metric_label_enums(parsed: List[tuple]) -> Dict[str, Dict[str, tuple]]:
    """``METRIC_LABELS`` from obs/metrics.py — the declared enum
    catalogue, read statically (it is a pure literal by contract)."""
    for rel, _src, tree in parsed:
        if not rel.endswith("obs/metrics.py"):
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "METRIC_LABELS"
                            for t in node.targets)):
                try:
                    return ast.literal_eval(node.value)
                except ValueError:
                    return {}
    return {}


def _literal_label_values(node: ast.AST) -> List[str]:
    """String literals an observation's label kwarg can evaluate to:
    a Constant, or both arms of a conditional expression ('true' if ok
    else 'false'). Empty = not statically resolvable."""
    if isinstance(node, ast.Constant):
        return [node.value] if isinstance(node.value, str) else []
    if isinstance(node, ast.IfExp):
        return (_literal_label_values(node.body)
                + _literal_label_values(node.orelse))
    return []


def _check_label_enums(parsed: List[tuple], fault_sites: Dict[str, str],
                       violations: List[str]) -> None:
    """Rule 5: labelled observations stay inside the declared enums."""
    var_map = _metric_var_map(parsed)
    enums = _metric_label_enums(parsed)
    for rel, _src, tree in parsed:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _OBS_METHODS):
                continue
            recv = node.func.value
            var = (recv.id if isinstance(recv, ast.Name)
                   else recv.attr if isinstance(recv, ast.Attribute)
                   else None)
            metric = var_map.get(var or "")
            if metric is None:
                continue  # not a metric object (Event.set, queue, ...)
            site = f"{rel}:{node.lineno}"
            declared = enums.get(metric, {})
            for kw in node.keywords:
                if kw.arg is None or kw.arg in _NON_LABEL_KWARGS:
                    continue
                if kw.arg in _BANNED_LABEL_KEYS:
                    violations.append(
                        f"{site}: metric {metric!r} labelled with "
                        f"{kw.arg!r} — per-request identity labels are "
                        f"unbounded cardinality, banned outright")
                    continue
                allowed = declared.get(kw.arg)
                if allowed is None:
                    violations.append(
                        f"{site}: metric {metric!r} label {kw.arg!r} has "
                        f"no declared enum in obs/metrics.py "
                        f"METRIC_LABELS — labelled observations must "
                        f"draw values from a fixed catalogue enum")
                    continue
                if isinstance(kw.value, ast.JoinedStr) or (
                        isinstance(kw.value, ast.Call)
                        and isinstance(kw.value.func, ast.Name)
                        and kw.value.func.id in ("str", "repr", "format")):
                    violations.append(
                        f"{site}: metric {metric!r} label {kw.arg!r} is "
                        f"computed (f-string/str()) — unbounded label "
                        f"values are banned; use an enum member")
                    continue
                if (isinstance(kw.value, ast.Constant)
                        and not isinstance(kw.value.value, str)):
                    violations.append(
                        f"{site}: metric {metric!r} label {kw.arg!r} is "
                        f"the non-string literal {kw.value.value!r} — "
                        f"request-id-shaped labels are banned")
                    continue
                for lit in _literal_label_values(kw.value):
                    if lit not in allowed:
                        violations.append(
                            f"{site}: metric {metric!r} label "
                            f"{kw.arg!r}={lit!r} outside the declared "
                            f"enum {tuple(allowed)}")
                # Plain names/attributes pass statically; the metric
                # classes validate them against the same enum at
                # observe time (obs/metrics.py _key).
    # The fault-trip site label must enumerate every wired site: a new
    # maybe_fail site without an enum entry would raise at first trip.
    trip_sites = enums.get("egpt_fault_trips_total", {}).get("site")
    if trip_sites is not None:
        for name, site in sorted(fault_sites.items()):
            if name not in trip_sites:
                violations.append(
                    f"{site}: fault site {name!r} missing from "
                    f"egpt_fault_trips_total's site enum "
                    f"(obs/metrics.py METRIC_LABELS) — its first trip "
                    f"would raise at observe time")


def _check_fault_coverage(root: str,
                          violations: List[str]) -> Dict[str, str]:
    """Rule 4: every wired fault site is reachable from a chaos/faults
    test (its literal name appears in a tests/ file that arms
    injection). The example spec in faults.py's own docstring names real
    sites, which is fine — they must be covered anyway. Returns the
    site -> first-wiring-site map (rule 5 cross-checks it against the
    egpt_fault_trips_total label enum)."""
    sites: Dict[str, str] = {}
    pkg = os.path.join(root, "eventgpt_tpu")
    for dirpath, _, files in os.walk(pkg):
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(dirpath, f)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path) as fh:
                src = fh.read()
            for m in _FAULT_SITE_RE.finditer(src):
                sites.setdefault(
                    m.group(1),
                    f"{rel}:{src.count(chr(10), 0, m.start()) + 1}")
    chaos_text = []
    tests = os.path.join(root, "tests")
    if os.path.isdir(tests):
        for f in sorted(os.listdir(tests)):
            if not f.endswith(".py"):
                continue
            with open(os.path.join(tests, f)) as fh:
                src = fh.read()
            if _FAULT_TEST_RE.search(src):
                chaos_text.append(src)
    blob = "\n".join(chaos_text)
    if not sites:
        if os.path.isdir(pkg):
            violations.append("no fault sites found under eventgpt_tpu/ — "
                              "the scan pattern changed under the lint")
        return sites
    for name, site in sorted(sites.items()):
        if name not in blob:
            violations.append(
                f"{site}: fault site {name!r} is not exercised by any "
                f"chaos/faults test (no tests/ file arming injection "
                f"mentions it) — unreachable failure handling rots")
    return sites


def _check_catalogue(root: str, seen: Dict[str, str],
                     violations: List[str]) -> None:
    """Rule 3: every registered egpt_* metric appears (by literal name)
    in OBSERVABILITY.md's catalogue."""
    doc_path = os.path.join(root, "OBSERVABILITY.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError:
        doc = ""
    for name, site in sorted(seen.items()):
        if METRIC_NAME_RE.match(name) and name not in doc:
            violations.append(
                f"{site}: metric {name!r} has no catalogue row in "
                f"OBSERVABILITY.md — document every registered metric")


def main() -> int:
    root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = run_lint(root)
    for v in violations:
        print(v)
    print(f"lint_telemetry: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
