#!/usr/bin/env python
"""Static telemetry lint (ISSUE 3 satellite; the fast tier runs it via
``tests/test_lint_telemetry.py``, or run it directly: prints violations
and exits non-zero when any exist).

Rule 1 — hot paths use ``time.perf_counter``, never ``time.time``:
wall-clock jumps (NTP slews, suspend/resume) would corrupt latency
histograms, deadlines and the pipelined-overlap accounting. Hot paths
are the serving scheduler, the obs package itself, the fault probes, the
jitted-step helpers, prefetch, and the kernels. Deliberate wall-clock
users stay OFF this list: ``train/resilience.py`` stamps heartbeat files
with epoch time for EXTERNAL watchdogs, and ``cli/serve.py``'s uptime is
human-facing.

Rule 2 — metric registration: every ``.counter(``/``.gauge(``/
``.histogram(`` call with a string-literal name uses a name matching
``egpt_[a-z0-9_]+``, and each name is registered exactly once across the
runtime tree (the obs/metrics.py central-catalogue rule: call sites
import metric objects, they never register). Tests are excluded — they
build private registries with throwaway names.

Rule 3 — catalogue coverage (ISSUE 4 satellite): every registered
``egpt_*`` metric has a row in OBSERVABILITY.md (literal name mention).
An operator hunting a dashboard number must find its meaning in the
catalogue; a metric that ships undocumented "passes" silently forever.

Rule 4 — fault-site test coverage (ISSUE 5 satellite): every
``faults.maybe_fail``/``maybe_delay`` site name wired in the runtime
tree (``eventgpt_tpu/``) appears, by literal name, in at least one
chaos/faults test — a tests/ file that actually arms injection
(``faults.configure(`` or ``EGPT_FAULTS``). A fault site nobody can
reach from a test is exactly the dead handling code ``faults.py``
exists to prevent.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List

HOT_PATHS = (
    "eventgpt_tpu/serve.py",
    "eventgpt_tpu/faults.py",
    "eventgpt_tpu/obs/",
    "eventgpt_tpu/train/steps.py",
    "eventgpt_tpu/train/prefetch.py",
    "eventgpt_tpu/ops/",
)
# Trees scanned for metric registrations (rule 2). tests/ is excluded on
# purpose: private test registries use throwaway names.
METRIC_SCAN = ("eventgpt_tpu", "scripts", "bench.py")

METRIC_NAME_RE = re.compile(r"^egpt_[a-z0-9_]+$")
_REG_RE = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*['\"]([A-Za-z0-9_.:-]+)['\"]")
# Rule 4: fault-probe call sites in the runtime tree (string-literal
# site names only — the grammar faults.py documents).
_FAULT_SITE_RE = re.compile(
    r"maybe_(?:fail|delay)\(\s*['\"]([A-Za-z0-9_.]+)['\"]")
# A tests/ file counts as a chaos/faults test iff it arms injection.
_FAULT_TEST_RE = re.compile(r"faults\.configure\(|EGPT_FAULTS")


def _is_hot(rel: str) -> bool:
    return any(rel == h or (h.endswith("/") and rel.startswith(h))
               for h in HOT_PATHS)


def _py_files(root: str) -> List[str]:
    out = []
    for scan in METRIC_SCAN:
        p = os.path.join(root, scan)
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, _, files in os.walk(p):
            out.extend(os.path.join(dirpath, f) for f in sorted(files)
                       if f.endswith(".py"))
    return sorted(out)


def _check_time_time(rel: str, tree: ast.AST, out: List[str]) -> None:
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "time"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "time"):
            out.append(f"{rel}:{node.lineno}: time.time() in a hot path "
                       f"(use time.perf_counter)")
        if (isinstance(node, ast.ImportFrom) and node.module == "time"
                and any(a.name == "time" for a in node.names)):
            out.append(f"{rel}:{node.lineno}: 'from time import time' in "
                       f"a hot path (use time.perf_counter)")


def run_lint(root: str) -> List[str]:
    """Returns the violation list (empty = clean)."""
    violations: List[str] = []
    seen: Dict[str, str] = {}  # metric name -> first registration site
    for path in _py_files(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path) as f:
            src = f.read()
        try:
            tree = ast.parse(src, rel)
        except SyntaxError as e:
            violations.append(f"{rel}: unparseable ({e})")
            continue
        if _is_hot(rel):
            _check_time_time(rel, tree, violations)
        for m in _REG_RE.finditer(src):
            # \s crosses newlines: registrations wrap the name to the
            # line after the call in the catalogue's house style.
            name = m.group(1)
            site = f"{rel}:{src.count(chr(10), 0, m.start()) + 1}"
            if not METRIC_NAME_RE.match(name):
                violations.append(
                    f"{site}: metric name {name!r} does not match "
                    f"{METRIC_NAME_RE.pattern}")
            if name in seen:
                violations.append(
                    f"{site}: metric {name!r} registered twice "
                    f"(first at {seen[name]}) — define metrics once, "
                    f"in obs/metrics.py")
            else:
                seen[name] = site
    if not seen:
        violations.append("no metric registrations found — the scan "
                          "pattern or tree layout changed under the lint")
    _check_catalogue(root, seen, violations)
    _check_fault_coverage(root, violations)
    return violations


def _check_fault_coverage(root: str, violations: List[str]) -> None:
    """Rule 4: every wired fault site is reachable from a chaos/faults
    test (its literal name appears in a tests/ file that arms
    injection). The example spec in faults.py's own docstring names real
    sites, which is fine — they must be covered anyway."""
    sites: Dict[str, str] = {}
    pkg = os.path.join(root, "eventgpt_tpu")
    for dirpath, _, files in os.walk(pkg):
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(dirpath, f)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path) as fh:
                src = fh.read()
            for m in _FAULT_SITE_RE.finditer(src):
                sites.setdefault(
                    m.group(1),
                    f"{rel}:{src.count(chr(10), 0, m.start()) + 1}")
    chaos_text = []
    tests = os.path.join(root, "tests")
    if os.path.isdir(tests):
        for f in sorted(os.listdir(tests)):
            if not f.endswith(".py"):
                continue
            with open(os.path.join(tests, f)) as fh:
                src = fh.read()
            if _FAULT_TEST_RE.search(src):
                chaos_text.append(src)
    blob = "\n".join(chaos_text)
    if not sites:
        if os.path.isdir(pkg):
            violations.append("no fault sites found under eventgpt_tpu/ — "
                              "the scan pattern changed under the lint")
        return
    for name, site in sorted(sites.items()):
        if name not in blob:
            violations.append(
                f"{site}: fault site {name!r} is not exercised by any "
                f"chaos/faults test (no tests/ file arming injection "
                f"mentions it) — unreachable failure handling rots")


def _check_catalogue(root: str, seen: Dict[str, str],
                     violations: List[str]) -> None:
    """Rule 3: every registered egpt_* metric appears (by literal name)
    in OBSERVABILITY.md's catalogue."""
    doc_path = os.path.join(root, "OBSERVABILITY.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError:
        doc = ""
    for name, site in sorted(seen.items()):
        if METRIC_NAME_RE.match(name) and name not in doc:
            violations.append(
                f"{site}: metric {name!r} has no catalogue row in "
                f"OBSERVABILITY.md — document every registered metric")


def main() -> int:
    root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = run_lint(root)
    for v in violations:
        print(v)
    print(f"lint_telemetry: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
