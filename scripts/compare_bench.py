#!/usr/bin/env python
"""Noise-aware regression gate for bench / workload JSON records.

Usage:
  python scripts/compare_bench.py BASE.json NEW.json [--tolerance 0.15]
         [--abs_floor 0.002] [--require KEY ...]

Diffs two bench records (``bench.py`` one-line records, the driver's
``BENCH_r0N.json`` wrapper — ``{"parsed": {...}}`` — or ``bench.py
--mode workload`` / ``WORKLOAD_r0N.json`` records) and exits non-zero
when any shared performance key regressed beyond the tolerance. This is
the hook later PRs cite instead of eyeballing numbers: "compare_bench
r(N) vs r(N-1) is clean" is a checkable claim; "the numbers look fine"
is not.

Design points (all learned from the repo's own measurement history,
PERFORMANCE.md):

  * **Direction-aware.** tok/s, goodput, ratios, MFU are
    higher-is-better; seconds/ms (TTFT, ITL, latency, stalls, step
    time) are lower-is-better. Keys whose direction cannot be inferred
    are reported as informational drift, never gated.
  * **Drift tolerance.** CPU throughput drifts ±15% between machine
    phases (the measured envelope; interleave A/B runs when a claim
    needs better), so the default gate fires only beyond 15%. Tighten
    with ``--tolerance`` for same-phase interleaved records.
  * **Absolute floor.** Two sub-``--abs_floor`` timings (default 2 ms)
    compare equal: at that scale the log2-bucket/scheduler jitter is
    bigger than the signal, and 0.001 s -> 0.002 s is not a 2x
    regression.
  * **Paired sweep points.** ``"sweep"`` lists (workload records) match
    pointwise by ``rate_mult``; ``"ab"`` interleaved arrays compare by
    their means.
  * **tok_s pairs only on trace identity.** A workload record's tok/s
    is (trace token budget) / duration, so it is only comparable across
    records generated with the SAME output-cap flags (``output_min`` /
    ``output_max``, recorded since ISSUE 8). Records whose identity
    differs — or predates the keys — have their tok_s keys dropped with
    a note; ``--require tok_s`` on such a pair fails loudly as
    not-comparable.

Only the performance-shaped keys are gated (``_GATE_PATTERNS``); config
echo keys (batch, chunk, seeds, counts) are identity context, not
metrics.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# Measured machine-phase drift envelope on CPU smoke runs
# (PERFORMANCE.md): regressions inside it are indistinguishable from
# noise in unpaired runs.
DEFAULT_TOLERANCE = 0.15
DEFAULT_ABS_FLOOR = 0.002  # seconds-scale values below this compare equal

# Key substrings that mark a value as a gated performance metric, with
# direction. Checked in order; first match wins.
_HIGHER = ("tok_s", "tokens_per_s", "goodput", "attainment", "hit_ratio",
           "met_ratio", "overlap_ratio", "mfu", "tokens_per_iteration",
           "goodput_ratio", "accounted_ratio",
           # Adaptive speculation (ISSUE 13): committed tokens per
           # segment dispatch — the number the 8x spec spread is decided
           # by. spec_depth_mean / spec_masked_rows / spec_accept_ema
           # stay deliberately direction-less: a different chosen depth
           # is a different policy, not a regression.
           "accepted_per_dispatch")
# Memory-ledger keys (ISSUE 9) gate lower-is-better: a grown resident
# peak or a grown unaccounted share is a regression under the same
# ±15% scheme (component echo keys carry no direction — informational).
# Flight-recorder keys (ISSUE 10): the per-class phase decomposition
# p99s (classes.<c>.queue_p99_s / defer / admission / decode /
# host_gap / failover_redo) ride the "_p99_s" pattern below, so a
# grown tail phase gates lower-is-better and sweep points pair by
# rate_mult like every other per-class percentile. The attribution
# SHARES (classes.<c>.attribution.*) and the miss-cause COUNTS
# (miss_causes.*) are deliberately direction-less — a shifted share is
# a different explanation, not a regression — but they are numeric
# leaves, so ``--require miss_causes`` fails loudly when a workload
# record stops carrying the breakdown.
_LOWER = ("ttft", "itl", "latency", "stall", "step_s", "step_time", "_ms",
          "wait", "duration_s", "first_request_s", "warmup_s", "_p50_s",
          "_p99_s", "_p95_s", "overhead_frac", "peak_bytes",
          "unaccounted_bytes")


def direction(key: str) -> Optional[int]:
    """+1 higher-is-better, -1 lower-is-better, None = not gated."""
    k = key.lower()
    for pat in _HIGHER:
        if pat in k:
            return +1
    for pat in _LOWER:
        if pat in k:
            return -1
    return None


def _unwrap(rec: Dict[str, Any]) -> Dict[str, Any]:
    """BENCH_r0N.json driver wrapper -> the bench record inside it."""
    if "parsed" in rec and isinstance(rec["parsed"], dict):
        return rec["parsed"]
    return rec


def _mean(v: Any) -> Optional[float]:
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return float(v)
    if (isinstance(v, list) and v
            and all(isinstance(x, (int, float)) and not isinstance(x, bool)
                    for x in v)):
        return sum(float(x) for x in v) / len(v)
    return None


def _flatten(rec: Dict[str, Any], prefix: str = "") -> Dict[str, float]:
    """Numeric leaves (lists -> means: the interleaved-A/B form), keyed
    by dotted path. ``sweep`` lists key by rate_mult so points pair."""
    out: Dict[str, float] = {}
    for k, v in rec.items():
        path = f"{prefix}{k}"
        if k == "sweep" and isinstance(v, list):
            for leg in v:
                if isinstance(leg, dict) and "rate_mult" in leg:
                    out.update(_flatten(
                        leg, f"{path}[x{leg['rate_mult']}]."))
            continue
        if isinstance(v, dict):
            out.update(_flatten(v, path + "."))
            continue
        m = _mean(v)
        if m is not None:
            out[path] = m
    return out


def _trace_identity(rec: Dict[str, Any]) -> Optional[Tuple]:
    """The keys that make two workload records' tok_s comparable: the
    trace is a pure function of (seed, requests, arrival, sessions,
    output caps), and with eos-free replay tok_s is (sum of budgets) /
    duration — so SAME identity = pairable, different or unrecorded =
    structurally skewed (ISSUE 8 satellite: WORKLOAD_r01's pre-fix
    tok_s implied ~1665 served tokens where the current trace budgets
    sum to 1151, because the output-cap flags at r01 time were never
    recorded). Returns None for non-workload records (no sweep), ()
    for a workload record that predates the cap keys."""
    r = _unwrap(rec)
    if "sweep" not in r:
        return None
    if "output_min" not in r or "output_max" not in r:
        return ()
    # proc_fleet joins the identity (ISSUE 11): N separate jax worker
    # PROCESSES contend for the same host CPUs, so tok_s across
    # process topologies measures the contention regime, not the
    # server — same-trace thread-fleet vs process-fleet records drop
    # tok_s with an unpaired note. (The in-process --fleet key stays
    # OUT of the identity on purpose: thread replicas share one
    # runtime, and the fleet-vs-single tok_s gate is load-bearing.)
    # kv_layout joins it too (ISSUE 12): the paged pool's block-table
    # gather is a real per-token cost, so dense-vs-paged tok_s measures
    # the layout, not drift — those records drop tok_s with an unpaired
    # note. Records predating the key are dense by construction.
    # The role split joins it last (ISSUE 17): a disaggregated fleet
    # runs admission and decode on DIFFERENT processes, so colocated-
    # vs-disagg tok_s measures the topology, not drift — the honest
    # cross-arm comparison is the SLO tails, which pair fine. Records
    # predating the key are colocated by construction.
    return (r.get("requests"), r.get("seed"), r.get("arrival"),
            r.get("sessions"), r["output_min"], r["output_max"],
            r.get("proc_fleet"), r.get("kv_layout") or "dense",
            r.get("proc_fleet_roles") or "colocated")


def compare(base: Dict[str, Any], new: Dict[str, Any],
            tolerance: float = DEFAULT_TOLERANCE,
            abs_floor: float = DEFAULT_ABS_FLOOR,
            require: Tuple[str, ...] = (),
            ) -> Tuple[List[str], List[str]]:
    """Returns (regressions, notes). Empty regressions = gate passes."""
    b = _flatten(_unwrap(base))
    n = _flatten(_unwrap(new))
    regressions: List[str] = []
    notes: List[str] = []
    bi, ni = _trace_identity(base), _trace_identity(new)
    if (bi is not None or ni is not None) and (not bi or bi != ni):
        # Workload records whose traces differ (or predate the cap
        # keys): tok_s depends on the trace's token budget, not the
        # server, so pairing it would gate noise. Drop those keys from
        # BOTH sides — a ``--require tok_s`` then fails loudly as
        # not-comparable instead of comparing apples to oranges.
        dropped = sorted(k for k in set(b) | set(n) if "tok_s" in k)
        for k in dropped:
            b.pop(k, None)
            n.pop(k, None)
        if dropped:
            notes.append(
                f"unpaired   tok_s ({len(dropped)} key(s)) not gated: "
                f"workload output-cap identity differs or is "
                f"unrecorded (base={bi}, new={ni})")
    # Memory keys pair only within one topology (ISSUE 9): a fleet
    # point's ledger peak covers N resident caches, a single-engine
    # point's covers one — cross-topology "regressions" there would be
    # architecture, not drift. Same design as the tok_s identity rule.
    # kv_layout joins the topology (ISSUE 12): a paged point's resident
    # bytes live in kv_pool/kv_block_table where a dense point's live in
    # kv_cache — cross-layout memory deltas are the layout change
    # itself, not drift.
    # proc_fleet_roles joins the topology too (ISSUE 17): a prefill
    # worker's resident bytes have no decode arena and vice versa.
    bt = (_unwrap(base).get("fleet"), _unwrap(base).get("proc_fleet"),
          _unwrap(base).get("kv_layout") or "dense",
          _unwrap(base).get("proc_fleet_roles") or "colocated")
    nt = (_unwrap(new).get("fleet"), _unwrap(new).get("proc_fleet"),
          _unwrap(new).get("kv_layout") or "dense",
          _unwrap(new).get("proc_fleet_roles") or "colocated")
    if bt != nt:
        dropped = sorted(k for k in set(b) | set(n)
                         if "mem_peak" in k or ".memory." in k
                         or "memory_bytes" in k)
        for k in dropped:
            b.pop(k, None)
            n.pop(k, None)
        if dropped:
            notes.append(
                f"unpaired   memory ({len(dropped)} key(s)) not gated: "
                f"replica topology differs (base fleet/proc={bt}, new "
                f"fleet/proc={nt}) — ledger peaks only pair within one "
                f"topology")
    for key in sorted(set(b) & set(n)):
        d = direction(key)
        if d is None:
            continue
        if require and not any(r in key for r in require):
            continue
        bv, nv = b[key], n[key]
        if d == -1 and abs(bv) < abs_floor and abs(nv) < abs_floor:
            continue  # both under the jitter floor: equal by fiat
        if bv == 0:
            continue  # no meaningful ratio (e.g. zeroed counter)
        change = (nv - bv) / abs(bv)
        worse = change * d < 0
        mag = abs(change)
        line = (f"{key}: {bv:.6g} -> {nv:.6g} "
                f"({'+' if change >= 0 else ''}{change * 100:.1f}%)")
        if worse and mag > tolerance:
            regressions.append("REGRESSION " + line)
        elif mag > tolerance:
            notes.append("improved   " + line)
        elif worse and mag > tolerance / 2:
            notes.append("drift      " + line)
    missing = [k for k in sorted(b) if k not in n and direction(k)]
    for k in missing:
        notes.append(f"missing    {k}: present in base, absent in new")
    if require:
        for r in require:
            if not any(r in k for k in set(b) & set(n)):
                regressions.append(
                    f"REGRESSION required key {r!r} not comparable "
                    f"(absent from one record)")
    return regressions, notes


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Diff two bench/workload JSONs; exit 1 on regression")
    p.add_argument("base")
    p.add_argument("new")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="relative regression gate (default 0.15 = the "
                        "measured CPU machine-phase drift; tighten for "
                        "interleaved same-phase records)")
    p.add_argument("--abs_floor", type=float, default=DEFAULT_ABS_FLOOR,
                   help="seconds-scale values both below this compare "
                        "equal (scheduler jitter floor)")
    p.add_argument("--require", nargs="*", default=[],
                   help="gate only keys containing these substrings, and "
                        "fail if any is not comparable")
    args = p.parse_args(argv)
    with open(args.base) as f:
        base = json.load(f)
    with open(args.new) as f:
        new = json.load(f)
    regressions, notes = compare(base, new, tolerance=args.tolerance,
                                 abs_floor=args.abs_floor,
                                 require=tuple(args.require))
    for line in notes:
        print(line)
    for line in regressions:
        print(line)
    print(f"compare_bench: {len(regressions)} regression(s), "
          f"{len(notes)} note(s), tolerance ±{args.tolerance * 100:.0f}%")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
