#!/usr/bin/env python
"""egpt-check runner: the unified static-analysis suite (ISSUE 8).

One report over every analyzer — the lock-discipline race detector
(``lock``), the host-sync hot-path lint (``hot-sync``), the jit-hygiene
lint (``jit-cache``), and the five telemetry rules migrated from
``lint_telemetry.py`` (``tele-*``). Non-zero exit on any unwaived
finding; the fast tier runs this via ``tests/test_egpt_check.py`` so
the shipped tree stays clean by construction.

Usage::

    python scripts/egpt_check.py [ROOT] [--json] [--rules ID[,ID...]]
                                 [--waived] [--list]

  * ``--json``   machine-readable report (stable keys + per-rule
    counts) so bench/CI tooling can diff finding counts across PRs;
  * ``--rules``  run a subset (ids from ``--list``);
  * ``--waived`` also print waived findings with their justifications;
  * ``--list``   print the rule catalogue and exit.

Annotation / waiver grammar: OBSERVABILITY.md "Static analysis".
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from eventgpt_tpu.analysis import (ALL_RULES, render_json, render_text,
                                   run_checks, unwaived)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Run the egpt-check static-analysis suite")
    p.add_argument("root", nargs="?", default=_REPO)
    p.add_argument("--json", action="store_true",
                   help="machine-readable report (diff finding counts "
                        "across PRs)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--waived", action="store_true",
                   help="also print waived findings + justifications")
    p.add_argument("--list", action="store_true",
                   help="print the rule catalogue and exit")
    args = p.parse_args(argv)

    rules = list(ALL_RULES)
    if args.list:
        for r in rules:
            print(f"{r.id:12s} {r.doc}")
        return 0
    if args.rules:
        want = {x.strip() for x in args.rules.split(",") if x.strip()}
        unknown = want - {r.id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {sorted(unknown)} "
                  f"(see --list)", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in want]

    findings = run_checks(args.root, rules)
    if args.json:
        print(render_json(findings, rules))
    else:
        print(render_text(findings, show_waived=args.waived))
    return 1 if unwaived(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
