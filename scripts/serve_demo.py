"""Continuous-batching server demo: N event-QA requests through one
resident decode batch (``eventgpt_tpu/serve.py``).

The reference answers one request per process (``inference.py``); here
requests join a running batch as rows free up — submit more queries than
``--max_batch`` and watch them stream through without a batch drain.

Threading note (audited by ``scripts/egpt_check.py``, ISSUE 8): this
demo drives the ``ContinuousBatcher`` from the main thread only —
consistent with the batcher's ``_EXTERNAL_LOCK`` single-owner contract
(here the owner is simply this script; no engine, no lock needed).
``scripts/`` is inside the suite's scan set, so a future edit that
spawns a thread around the batcher or mints an untracked jit gets
flagged, not merged.

Usage (offline smoke, tiny random weights):
  python scripts/serve_demo.py --event_frame /root/reference/samples/sample1.npy \
      --queries "What is happening?;Describe the scene.;What moves fastest?" \
      --max_batch 2 --max_new_tokens 24
Real checkpoints: --model_path <hf dir> (same loader as cli/infer.py).
"""

from __future__ import annotations

import argparse
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model_path", default="tiny-random")
    p.add_argument("--tokenizer_path", default=None)
    p.add_argument("--event_frame", required=True,
                   help="event .npy to answer about; with --event_root, a "
                        "path relative to (and confined under) that root")
    p.add_argument("--event_root", default=None,
                   help="optional allowlist root: --event_frame must "
                        "resolve inside it (same confinement as "
                        "cli/serve.py — set this when the frame name "
                        "comes from anything other than your own shell)")
    p.add_argument("--queries", required=True,
                   help="';'-separated natural-language questions")
    p.add_argument("--conv_mode", default="eventgpt_v1")
    p.add_argument("--max_batch", type=int, default=2)
    p.add_argument("--max_len", type=int, default=1024)
    p.add_argument("--chunk", type=int, default=32)
    p.add_argument("--max_new_tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--dtype", default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--quant", default="none", choices=["none", "int8", "int4"])
    p.add_argument("--fuse_params", action="store_true",
                   help="fuse qkv / gate-up before quantization (+4%% at "
                        "wide batches — PERFORMANCE.md)")
    p.add_argument("--kv_cache", default="bf16", choices=["bf16", "int8"])
    p.add_argument("--speculative", type=int, default=0,
                   help="verify-window size K (0 = plain decode)")
    p.add_argument("--draft_head", default=None,
                   help="trained Medusa head stack (.npz) for speculative "
                        "drafting (requires --speculative > 0)")
    p.add_argument("--warmup", action="store_true",
                   help="precompile every (bucket, segment) executable "
                        "before serving (ContinuousBatcher.warmup)")
    p.add_argument("--prefill_chunk", type=int, default=0,
                   help="decode-interleaved admission prefill chunk "
                        "(0 = one-shot admission prefill)")
    p.add_argument("--no_pipeline", action="store_true",
                   help="disable the pipelined scheduler (synchronous "
                        "segment dispatch; chains are identical either "
                        "way)")
    p.add_argument("--first_chunk", type=int, default=0,
                   help="TTFT ramp: short segment while a fresh admission "
                        "owes its first token (0 = off)")
    p.add_argument("--mesh_data", type=int, default=1)
    p.add_argument("--mesh_fsdp", type=int, default=1)
    p.add_argument("--mesh_model", type=int, default=1)
    p.add_argument("--seed", type=int, default=0)
    # prepare_model (shared with the infer/eval CLIs) reads these:
    p.add_argument("--use_event_qformer", action="store_true")
    p.add_argument("--pretrain_query_embedder", default=None)
    p.add_argument("--pretrain_attention_layers", default=None)
    args = p.parse_args(argv)

    frame = args.event_frame
    if args.event_root is not None:
        # Fail before touching the model: same confinement as cli/serve.py.
        from eventgpt_tpu.utils.paths import resolve_event_path

        frame = resolve_event_path(args.event_root, frame)

    from eventgpt_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    from eventgpt_tpu.cli.infer import load_model, prepare_model
    from eventgpt_tpu.data.conversation import prepare_event_prompt
    from eventgpt_tpu.data.tokenizer import tokenize_with_event
    from eventgpt_tpu.ops.image import process_event_file
    from eventgpt_tpu.serve import ContinuousBatcher

    from eventgpt_tpu.parallel.serving import build_serving_mesh

    cfg, params, tokenizer = load_model(
        args.model_path, args.dtype, None, args.tokenizer_path
    )
    # Mesh goes through prepare_model so the host tree lands sharded —
    # never a full unsharded copy on one chip first (cli/serve.py has the
    # same rule).
    mesh = build_serving_mesh(args.mesh_data, args.mesh_fsdp, args.mesh_model)
    cfg, params = prepare_model(cfg, params, tokenizer, args, mesh=mesh)
    _, pixels = process_event_file(
        frame, cfg.num_event_frames, cfg.vision.image_size
    )

    draft_head = None
    if args.draft_head:
        from eventgpt_tpu.models.medusa import load_medusa

        draft_head = load_medusa(args.draft_head)
    srv = ContinuousBatcher(
        params, cfg, max_batch=args.max_batch, max_len=args.max_len,
        chunk=args.chunk, temperature=args.temperature,
        eos_token_id=getattr(tokenizer, "eos_token_id", None),
        kv_quant=args.kv_cache == "int8", speculative=args.speculative,
        mesh=mesh, prefill_chunk=args.prefill_chunk,
        draft_head=draft_head, first_chunk=args.first_chunk,
        pipeline=not args.no_pipeline,
    )
    if args.warmup:
        t0 = time.perf_counter()
        n = srv.warmup()
        print(f"[warmup: {n} executables in {time.perf_counter() - t0:.2f}s]")
    queries = [q for q in args.queries.split(";") if q.strip()]
    t0 = time.perf_counter()
    rids = {}
    for q in queries:
        ids = tokenize_with_event(
            prepare_event_prompt(q.strip(), args.conv_mode), tokenizer
        )
        rids[srv.submit(ids, pixels, args.max_new_tokens)] = q.strip()
    out = srv.run_until_drained()
    dt = time.perf_counter() - t0
    tot = 0
    for rid, q in rids.items():
        answer = tokenizer.batch_decode([out[rid]],
                                        skip_special_tokens=True)[0].strip()
        tot += len(out[rid])
        print(f"Q: {q}\nA: {answer}\n")
    print(f"[{len(queries)} requests, {tot} tokens, {dt:.2f}s, "
          f"{tot / dt:.1f} tok/s aggregate]")
    return out


if __name__ == "__main__":
    main()
