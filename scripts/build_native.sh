#!/usr/bin/env bash
# Build the native preprocessing toolchain + ctypes library.
# Usage: scripts/build_native.sh [address|thread]  (optional sanitizer mode)
set -euo pipefail
cd "$(dirname "$0")/.."

SAN="${1:-}"
BUILD=native/build
ARGS=(-DCMAKE_BUILD_TYPE=Release)
if [[ -n "$SAN" ]]; then
  BUILD="native/build-${SAN}"
  ARGS+=(-DEGPT_SANITIZE="$SAN")
fi

cmake -S native -B "$BUILD" "${ARGS[@]}"
cmake --build "$BUILD" -j"$(nproc)"
ctest --test-dir "$BUILD" --output-on-failure
